"""Request-lifecycle tracing, the /metrics surface, and SLO capture
(paddle_tpu.observability.trace + serving plumbing — ISSUE 12).

The load-bearing claims: (1) phase accounting is EXACT — a trace's
queue_ms + prefill_ms + decode_ms equals its wall_ms as reported,
including across preempt→restore cycles and replica-failure evacuation;
(2) the trace id survives every lifecycle detour (the tracer is keyed
by request id and the id rides Request.trace_id); (3) the operational
surfaces — Prometheus /metrics, GET /v1/requests, the Perfetto export —
render valid artifacts from the same producers.
"""

import json
import os
import re
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu import resilience as rs
from paddle_tpu import serving
from paddle_tpu.observability.sinks import (prom_name, prom_split,
                                            registry_to_prometheus)
from paddle_tpu.observability.trace import RequestTracer, SLOCapture
from paddle_tpu.serving.distributed import EngineReplicaSet

R = np.random.default_rng(0)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prompt(n):
    return R.integers(0, 256, size=n).astype(np.int32)


@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


@pytest.fixture
def tel():
    t = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
    try:
        yield t
    finally:
        obs.disable()


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(model, **kw).warmup()


def _phases(tl):
    return [e["phase"] for e in tl["events"]]


def _assert_exact_sum(tl):
    s = tl["summary"]
    assert abs(s["queue_ms"] + s["prefill_ms"] + s["decode_ms"]
               - s["wall_ms"]) < 1e-9, s


# ---------------------------------------------------------------------------
# prometheus exposition (sinks.py)
# ---------------------------------------------------------------------------

class TestProm:
    def test_prom_split_grammar(self):
        assert prom_split("serve.replica[0].free_blocks") == \
            ("serve_replica_free_blocks", [("replica", "0")])
        assert prom_split("serve.tenant[acme].ttft_ms") == \
            ("serve_tenant_ttft_ms", [("tenant", "acme")])
        assert prom_split("span[ckpt.save].ms") == \
            ("span_ms", [("span", "ckpt.save")])
        assert prom_split("serve.tok_s") == ("serve_tok_s", [])
        # sanitation: prom name charset only
        name, _ = prom_split("weird-name.with+chars")
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name)
        assert prom_name("9lives") == "_9lives"

    def test_registry_to_prometheus_valid_exposition(self):
        from paddle_tpu.observability.registry import MetricsRegistry
        reg = MetricsRegistry()
        reg.counter("serve.requests").inc(3)
        reg.gauge("serve.replica[0].free_blocks").set(12)
        reg.gauge("serve.replica[1].free_blocks").set(7)
        reg.gauge("serve.broken").set("not-a-number")   # must be skipped
        h = reg.histogram("serve.ttft_ms")
        for v in (10.0, 20.0, 30.0):
            h.observe(v)
        body = registry_to_prometheus(reg, extra={"serve.live": 1,
                                                  "serve.requests": 99})
        sample = re.compile(
            r"[a-zA-Z_:][a-zA-Z0-9_:]*"
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? -?[0-9.eE+-]+')
        typed = set()
        for line in body.strip().splitlines():
            if line.startswith("# TYPE "):
                typed.add(line.split()[2])
                continue
            assert sample.fullmatch(line), line
            # TYPE precedes samples of its series
            base = re.match(r"[a-zA-Z0-9_:]+", line).group(0)
            assert any(base.startswith(t) for t in typed), line
        assert 'serve_replica_free_blocks{replica="0"} 12' in body
        assert 'serve_ttft_ms{quantile="0.95"} 30.0' in body
        assert "serve_ttft_ms_count 3" in body
        assert "broken" not in body
        assert "serve_live 1" in body
        assert "serve_requests 3" in body       # registry wins over extra
        assert "99" not in body

    def test_prometheus_without_registry_renders_extra(self):
        body = registry_to_prometheus(None, extra={"serve.queue_depth": 2})
        assert "# TYPE serve_queue_depth gauge" in body
        assert "serve_queue_depth 2" in body


# ---------------------------------------------------------------------------
# tracer unit (deterministic fake clock)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 100.0

    def tick(self, s):
        self.t += s

    def __call__(self):
        return self.t


class TestTracerUnit:
    def test_phases_sum_exactly_to_wall(self):
        clk = _Clock()
        tr = RequestTracer(clock=clk)
        tr.begin("r1", tenant="t")
        clk.tick(0.010)
        tr.transition("r1", "prefill", event="admit")
        clk.tick(0.020)
        tr.transition("r1", "decode", event="first_token")
        clk.tick(0.030)
        tr.retire("r1", reason="length", tokens=3)
        tl = tr.timeline("r1")
        s = tl["summary"]
        assert s["queue_ms"] == 10.0 and s["prefill_ms"] == 20.0
        assert s["decode_ms"] == 30.0 and s["wall_ms"] == 60.0
        assert s["done"] and s["reason"] == "length"
        _assert_exact_sum(tl)

    def test_preempt_episodes_accumulate(self):
        clk = _Clock()
        tr = RequestTracer(clock=clk)
        tr.begin("r1")
        clk.tick(0.005)
        tr.transition("r1", "decode", event="admit")
        clk.tick(0.010)
        tr.transition("r1", "queue", event="preempt")   # back to queue
        clk.tick(0.007)
        tr.transition("r1", "decode", event="admit")
        clk.tick(0.002)
        tr.retire("r1", tokens=1)
        s = tr.timeline("r1")["summary"]
        assert s["queue_ms"] == 12.0 and s["decode_ms"] == 12.0
        assert s["preempts"] == 1
        _assert_exact_sum(tr.timeline("r1"))

    def test_begin_is_get_or_create(self):
        tr = RequestTracer()
        a = tr.begin("r1", trace_id="outer")
        b = tr.begin("r1", trace_id="other")    # door→engine double begin
        assert a == b == "outer"
        assert _phases(tr.timeline("r1")).count("submit") == 1

    def test_trace_context_propagates(self):
        tr = RequestTracer()
        with obs.trace_context("ctx-id") as tid:
            assert tid == "ctx-id"
            assert tr.begin("r1") == "ctx-id"
        assert tr.begin("r2").startswith("tr-")   # generated outside

    def test_unknown_rid_is_noop(self):
        tr = RequestTracer()
        tr.point("ghost", "prefill_chunk")
        tr.transition("ghost", "decode")
        tr.retire("ghost")
        assert tr.timeline("ghost") is None

    def test_events_bounded_retire_forced(self):
        tr = RequestTracer(max_events=4)
        tr.begin("r1")
        for _ in range(10):
            tr.point("r1", "prefill_chunk", tokens=1)
        tr.retire("r1", reason="length", tokens=1)
        tl = tr.timeline("r1")
        assert len(tl["events"]) == 5               # 4 + forced retire
        assert tl["events"][-1]["phase"] == "retire"
        assert tl["summary"]["dropped_events"] == 7
        assert tl["summary"]["prefill_chunks"] == 10   # counted, not dropped

    def test_retention_bounded(self):
        tr = RequestTracer(capacity=3)
        for i in range(6):
            tr.begin(f"r{i}")
            tr.retire(f"r{i}")
        assert len(tr) == 3
        assert tr.timeline("r0") is None and tr.timeline("r5") is not None

    def test_retire_emits_serve_trace(self):
        events = []
        tr = RequestTracer(emit=events.append)
        tr.begin("r1", tenant="acme")
        tr.retire("r1", reason="eos", tokens=2)
        assert len(events) == 1
        ev = events[0]
        assert ev["event"] == "serve_trace" and ev["id"] == "r1"
        assert ev["tenant"] == "acme" and ev["summary"]["done"]
        json.dumps(ev)                              # JSONL-serializable

    def test_reused_request_id_starts_a_fresh_trace(self):
        """A request id legitimately reused (the engine's keep_finished
        window is smaller than trace_capacity) must not append onto the
        retired timeline — the second request gets its own trace and
        its own serve_trace event."""
        events = []
        tr = RequestTracer(emit=events.append)
        tr.begin("dup", trace_id="first")
        tr.retire("dup", reason="eos", tokens=1)
        tid2 = tr.begin("dup", trace_id="second")
        assert tid2 == "second"
        tr.transition("dup", "decode", event="admit")
        tr.retire("dup", reason="length", tokens=2)
        assert [e["trace_id"] for e in events] == ["first", "second"]
        tl = tr.timeline("dup")
        assert tl["trace_id"] == "second"
        assert _phases(tl).count("retire") == 1
        # late events for an already-retired trace are dropped, never
        # appended past its retire
        tr.point("dup", "prefill_chunk")
        tr.transition("dup", "queue")
        assert _phases(tr.timeline("dup"))[-1] == "retire"

    def test_find_by_trace_id(self):
        tr = RequestTracer()
        with obs.trace_context("batch-7"):
            tr.begin("a")
            tr.begin("b")
        assert {t.request_id for t in tr.find("batch-7")} == {"a", "b"}


# ---------------------------------------------------------------------------
# SLO-triggered capture
# ---------------------------------------------------------------------------

class _FakeProf:
    def __init__(self):
        self.steps = 0
        self.stopped = False

    def step(self):
        self.steps += 1

    def stop(self):
        self.stopped = True


class TestSLOCapture:
    def _seed_ttft(self, n=10, ms=100.0):
        reg = obs.get_registry()
        for _ in range(n):
            reg.histogram("serve.ttft_ms").observe(ms)

    def test_arms_after_consecutive_breaches(self, tel, tmp_path):
        profs = []

        def factory(d):
            p = _FakeProf()
            profs.append((d, p))
            return p

        cap = SLOCapture(50.0, str(tmp_path), window_steps=2, windows=2,
                         capture_steps=3, min_samples=4,
                         profiler_factory=factory)
        self._seed_ttft()
        for _ in range(3):
            cap.on_step()
        assert not cap.capturing            # only 1 breached window yet
        cap.on_step()                       # window 2 → armed
        assert cap.capturing and len(profs) == 1
        for _ in range(3):
            cap.on_step()                   # countdown
        assert not cap.capturing and profs[0][1].stopped
        assert profs[0][1].steps == 3
        assert cap.captures == [profs[0][0]]
        evs = tel.sinks[0].events("serve_slo_capture")
        assert [e["state"] for e in evs] == ["armed", "done"]
        assert evs[1]["trace_dir"] == profs[0][0]
        assert tel.registry.snapshot()["serve.slo_captures"] == 1

    def test_healthy_window_resets_and_max_captures(self, tel, tmp_path):
        made = []
        cap = SLOCapture(50.0, str(tmp_path), window_steps=1, windows=2,
                         capture_steps=1, max_captures=1, min_samples=2,
                         profiler_factory=lambda d: (made.append(d)
                                                     or _FakeProf()))
        self._seed_ttft(ms=100.0)
        cap.on_step()                       # breach 1
        self._seed_ttft(n=512, ms=1.0)      # flush the window healthy
        cap.on_step()                       # healthy → reset
        self._seed_ttft(n=512, ms=100.0)
        cap.on_step()                       # breach 1 again
        assert not cap.capturing
        cap.on_step()                       # breach 2 → armed
        cap.on_step()                       # capture step → done
        for _ in range(8):
            cap.on_step()                   # max_captures=1: never re-arms
        assert len(made) == 1 and len(cap.captures) == 1

    def test_no_signal_never_arms(self, tel, tmp_path):
        cap = SLOCapture(50.0, str(tmp_path), window_steps=1, windows=1,
                         min_samples=8,
                         profiler_factory=lambda d: _FakeProf())
        for _ in range(10):
            cap.on_step()                   # no ttft observations at all
        assert not cap.capturing and not cap.captures

    def test_engine_wiring(self, tiny_llama, tel, tmp_path):
        profs = []

        def factory(d):
            p = _FakeProf()
            profs.append(p)
            return p

        cap = SLOCapture(1e-9, str(tmp_path), window_steps=1, windows=1,
                         capture_steps=2, min_samples=1,
                         profiler_factory=factory)
        eng = _engine(tiny_llama, slo_capture=cap)
        eng.add_request(_prompt(12), max_new_tokens=6)
        eng.run()
        # any real TTFT breaches 1e-9 ms: the engine's step hook armed
        # the capture and counted it down through the compiled steps
        assert profs and profs[0].stopped and profs[0].steps == 2
        assert len(cap.captures) == 1

    def test_windowed_profiler_smoke(self, tmp_path):
        # the default factory's host half: starts, steps, stops cleanly
        # (timer_only-style use; the device trace itself is exercised by
        # the profiler suite)
        from paddle_tpu.profiler import windowed_profiler
        prof = windowed_profiler(str(tmp_path / "w"), steps=2)
        try:
            prof.step()
            prof.step()
        finally:
            prof.stop()
        assert os.path.isdir(str(tmp_path / "w"))


# ---------------------------------------------------------------------------
# engine lifecycle tracing (real tiny model)
# ---------------------------------------------------------------------------

class TestEngineTracing:
    def test_lifecycle_phases_exactly_once(self, tiny_llama, tel):
        eng = _engine(tiny_llama)
        rids = [eng.add_request(_prompt(20), max_new_tokens=4,
                                tenant="acme"),
                eng.add_request(_prompt(5), max_new_tokens=3)]
        outs = eng.run()
        tr = obs.get_request_tracer()
        assert tr is tel.tracer is not None
        for rid in rids:
            tl = tr.timeline(rid)
            phases = _phases(tl)
            for ph in ("submit", "admit", "first_token", "retire"):
                assert phases.count(ph) == 1, (rid, phases)
            assert phases.index("submit") < phases.index("admit") \
                < phases.index("first_token") < phases.index("retire")
            _assert_exact_sum(tl)
            s = tl["summary"]
            assert s["done"] and s["decode_tokens"] == len(outs[rid])
        # the 20-token prompt prefilled in 8-token chunks: 3 chunks
        assert tr.timeline(rids[0])["summary"]["prefill_chunks"] == 3
        # phase histograms + per-tenant aggregates landed
        snap = tel.registry.snapshot()
        assert snap["serve.queue_ms"]["count"] >= 2
        assert snap["serve.prefill_ms"]["count"] == 2
        assert snap["serve.decode_ms_per_token"]["count"] == 2
        assert snap["serve.tenant[acme].ttft_ms"]["count"] == 1
        assert snap["serve.tenant[acme].queue_ms"]["count"] >= 1
        # one serve_trace event per retired request
        assert len(tel.sinks[0].events("serve_trace")) == 2

    def test_trace_id_from_context_and_request(self, tiny_llama, tel):
        eng = _engine(tiny_llama)
        with obs.trace_context("client-abc"):
            rid = eng.add_request(_prompt(6), max_new_tokens=2)
        eng.run()
        tr = obs.get_request_tracer()
        tl = tr.timeline(rid)
        assert tl["trace_id"] == "client-abc"
        # the id also rides the Request (survives state migration)
        assert eng._states[rid].request.trace_id == "client-abc"

    def test_preempt_restore_continuity(self, tiny_llama, tel):
        eng = _engine(tiny_llama)
        rid = eng.add_request(_prompt(12), max_new_tokens=8)
        eng.step()
        eng.step()          # prefill done, decoding
        tr = obs.get_request_tracer()
        tid_before = tr.timeline(rid)["trace_id"]
        assert eng.preempt(rid)
        outs = eng.run()
        assert len(outs[rid]) == 8
        tl = tr.timeline(rid)
        assert tl["trace_id"] == tid_before
        phases = _phases(tl)
        assert phases.count("preempt") == 1 \
            and phases.count("restore") == 1
        # re-admission: one admit per queue episode
        assert phases.count("admit") == 1 + tl["summary"]["preempts"]
        for ph in ("submit", "first_token", "retire"):
            assert phases.count(ph) == 1
        _assert_exact_sum(tl)
        # the preempt wait is queue time: two queue episodes observed
        assert tel.registry.snapshot()["serve.queue_ms"]["count"] == 2

    def test_isolated_failure_traced(self, tiny_llama, tel):
        eng = _engine(tiny_llama)
        rid = eng.add_request(_prompt(5), max_new_tokens=3)
        rs.install_faults("serve.step@0")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                outs = eng.run()
        finally:
            rs.clear_faults()
        assert len(outs[rid]) == 3
        tl = obs.get_request_tracer().timeline(rid)
        phases = _phases(tl)
        assert "isolated" in phases and phases.count("retire") == 1
        _assert_exact_sum(tl)

    def test_tracing_off_is_off(self, tiny_llama):
        tel = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False,
                         request_tracing=False)
        try:
            assert obs.get_request_tracer() is None
            eng = _engine(tiny_llama)
            rid = eng.add_request(_prompt(5), max_new_tokens=2)
            eng.run()
            assert eng._states[rid].request.trace_id is None
            assert not tel.sinks[0].events("serve_trace")
            assert "serve.queue_ms" not in tel.registry.snapshot()
        finally:
            obs.disable()


# ---------------------------------------------------------------------------
# front-door integration: per-tenant SLO + shed-path trace hygiene
# ---------------------------------------------------------------------------

class TestFrontDoorTracing:
    def test_per_tenant_slo_exemption_and_recovery(self, tiny_llama,
                                                   tel):
        """The global TTFT signal GATES the SLO shed; the submitting
        tenant's own aggregate refines it (healthy tenant exempt), and
        a shed tenant recovers when the global signal recovers — its
        frozen per-tenant window must not lock it out forever."""
        eng = _engine(tiny_llama)
        door = serving.FrontDoor(eng, policies={
            "lo": serving.TenantPolicy(priority=0),
            "ok": serving.TenantPolicy(priority=0)},
            slo_ttft_p95_ms=50.0)
        reg = tel.registry
        for _ in range(4):
            reg.histogram("serve.ttft_ms").observe(500.0)   # breached
            reg.histogram("serve.tenant[ok].ttft_ms").observe(1.0)
            reg.histogram("serve.tenant[lo].ttft_ms").observe(500.0)
        assert door.submit(_prompt(3), tenant="ok",
                           max_new_tokens=2).admitted      # own p95 ok
        a = door.submit(_prompt(3), tenant="lo", max_new_tokens=2)
        assert not a.admitted and a.reason == "slo_shed"
        b = door.submit(_prompt(3), tenant="new", max_new_tokens=2)
        assert not b.admitted                  # no history → global
        # recovery: the global window refreshes healthy; 'lo's frozen
        # per-tenant history no longer matters once the gate is open
        for _ in range(512):
            reg.histogram("serve.ttft_ms").observe(1.0)
        assert door.submit(_prompt(3), tenant="lo",
                           max_new_tokens=2).admitted
        door.run()

    def test_pump_shed_retires_trace(self, tiny_llama, tel):
        """A request answered admitted=True but shed at pump (the
        engine refused an already-vetted id) must not leak a live
        trace — tracer retention only reaps done traces."""
        from paddle_tpu.serving.errors import AdmissionError
        eng = _engine(tiny_llama)
        door = serving.FrontDoor(eng)
        orig = eng.add_request

        def boom(*a, **kw):
            eng.add_request = orig             # refuse exactly once
            raise AdmissionError("id raced into the retained set")

        eng.add_request = boom
        a = door.submit(_prompt(5), max_new_tokens=2)
        assert a.admitted                      # answered before pump
        t = obs.get_request_tracer().get(a.request_id)
        assert t is not None and t.done and t.finish_reason == "shed"
        assert tel.sinks[0].events("serve_shed")
        door.run()


# ---------------------------------------------------------------------------
# replica-failure evacuation keeps the trace
# ---------------------------------------------------------------------------

class TestReplicaEvacuationTracing:
    def _rset(self, model_fn):
        return EngineReplicaSet(
            [_engine(model_fn()) for _ in range(2)])

    def test_trace_survives_evacuation(self, tel):
        from paddle_tpu.models.llama import llama

        def build():
            pt.seed(0)
            return llama("tiny")

        rset = self._rset(build)
        prompts = [_prompt(n) for n in (9, 14, 6, 11)]
        rids = []
        rs.install_faults("serve.replica@4")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for p in prompts:
                    rids.append(rset.add_request(p, max_new_tokens=6))
                    rset.step()
                outs = rset.run()
        finally:
            rs.clear_faults()
        assert rset.failures == 1 and rset.requeued >= 1
        tr = obs.get_request_tracer()
        migrated = 0
        for rid in rids:
            assert len(outs[rid]) == 6
            tl = tr.timeline(rid)
            assert tl is not None and tl["summary"]["done"]
            phases = _phases(tl)
            assert phases.count("submit") == 1
            assert phases.count("retire") == 1
            assert phases.count("route") == 1
            _assert_exact_sum(tl)
            migrated += phases.count("migrate")
            # the trace id is intact on the (possibly migrated) state
            assert rset._states[rid].request.trace_id == tl["trace_id"]
        assert migrated == rset.requeued

    def test_hard_reset_keeps_first_token_exactly_once(self, tel):
        """When the failing replica cannot even swap out, the victim
        re-prefills from scratch on the survivor — the trace records
        the degraded path (reset_fresh + re_prefilled) while
        `first_token` stays exactly-once and sums stay exact."""
        from paddle_tpu.models.llama import llama

        def build():
            pt.seed(0)
            return llama("tiny")

        rset = self._rset(build)
        rids = []
        rs.install_faults("serve.replica@4,serve.swap@0x999")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for n in (9, 14, 6, 11):
                    rids.append(rset.add_request(_prompt(n),
                                                 max_new_tokens=6))
                    rset.step()
                outs = rset.run()
        finally:
            rs.clear_faults()
        assert rset.failures == 1
        tr = obs.get_request_tracer()
        resets = 0
        for rid in rids:
            assert len(outs[rid]) == 6
            tl = tr.timeline(rid)
            phases = _phases(tl)
            assert phases.count("first_token") == 1, (rid, phases)
            assert phases.count("retire") == 1
            resets += phases.count("reset_fresh")
            _assert_exact_sum(tl)
        assert resets >= 1, "no trace recorded the degraded reset path"


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------

class TestServerEndpoints:
    @pytest.fixture
    def server(self, tiny_llama, tel):
        eng = _engine(tiny_llama, max_batch=2)
        srv = serving.ServingServer(eng, poll_s=0.001)
        host, port = srv.start()
        import http.client
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            yield srv, conn
        finally:
            conn.close()
            srv.close()

    def _post(self, conn, body, headers=None):
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json", **(headers or {})})
        r = conn.getresponse()
        return r.status, json.loads(r.read())

    def test_metrics_and_timeline_endpoints(self, server):
        srv, conn = server
        status, out = self._post(
            conn, {"prompt": [3, 5, 7, 9], "max_tokens": 3},
            headers={"X-Trace-Id": "edge-42"})
        assert status == 200
        rid = out["id"]
        assert len(out["choices"][0]["token_ids"]) == 3

        conn.request("GET", f"/v1/requests/{rid}")
        r = conn.getresponse()
        tl = json.loads(r.read())
        assert r.status == 200
        assert tl["trace_id"] == "edge-42"
        phases = [e["phase"] for e in tl["events"]]
        for ph in ("submit", "admit", "first_token", "retire"):
            assert phases.count(ph) == 1
        _assert_exact_sum(tl)

        conn.request("GET", "/v1/requests/no-such")
        r = conn.getresponse()
        assert r.status == 404
        r.read()

        conn.request("GET", "/metrics")
        r = conn.getresponse()
        body = r.read().decode()
        assert r.status == 200
        assert "text/plain" in r.getheader("Content-Type")
        assert "# TYPE serve_ttft_ms summary" in body
        assert "serve_requests 1" in body
        assert re.search(r"serve_queue_ms_count \d+", body)

    def test_metrics_without_telemetry(self, tiny_llama):
        # no obs.enable(): the endpoint still renders engine-local
        # gauges, and /v1/requests answers the typed 503
        eng = _engine(tiny_llama)
        srv = serving.ServingServer(eng, poll_s=0.001)
        host, port = srv.start()
        import http.client
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("GET", "/metrics")
            r = conn.getresponse()
            body = r.read().decode()
            assert r.status == 200 and "serve_queue_depth 0" in body
            conn.request("GET", "/v1/requests/x")
            r = conn.getresponse()
            assert r.status == 503
            assert "tracing_disabled" in r.read().decode()
        finally:
            conn.close()
            srv.close()


# ---------------------------------------------------------------------------
# tools: trace_export + telemetry_report folding
# ---------------------------------------------------------------------------

class TestTraceTools:
    @pytest.fixture
    def jsonl(self, tiny_llama, tmp_path):
        path = str(tmp_path / "run.jsonl")
        obs.enable(jsonl_path=path, crash_hooks=False)
        try:
            eng = _engine(tiny_llama)
            for n, t in ((12, "acme"), (5, "bob")):
                eng.add_request(_prompt(n), max_new_tokens=3, tenant=t)
                eng.step()
            eng.run()
        finally:
            obs.disable()
        return path

    def test_trace_export_chrome_json(self, jsonl, tmp_path):
        out = str(tmp_path / "trace.json")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "trace_export.py"),
             jsonl, "-o", out],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        assert summary["requests"] == 2 and summary["out"] == out
        with open(out) as f:
            trace = json.load(f)
        evs = trace["traceEvents"]
        # every request has a named track, phase slices, and markers
        names = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert len(names) == 2
        slices = [e for e in evs if e["ph"] == "X"]
        assert {"queue", "prefill", "decode"} <= {e["name"]
                                                 for e in slices}
        for e in slices:
            assert e["dur"] >= 0 and {"pid", "tid", "ts"} <= set(e)
        assert any(e["ph"] == "i" and e["name"] == "prefill_chunk"
                   for e in evs)

    def test_export_pid_follows_migration(self):
        """An evacuated request's post-migration slices must render
        under the SURVIVOR replica's process, not the dead one's."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import trace_export
        ev = {"event": "serve_trace", "id": "r1", "trace_id": "t",
              "t0": 1.0, "events": [
                  {"phase": "submit", "t_ms": 0.0},
                  {"phase": "route", "t_ms": 0.1, "replica": 0},
                  {"phase": "admit", "t_ms": 0.2, "closed": "queue",
                   "ms": 0.2},
                  {"phase": "preempt", "t_ms": 1.0, "closed": "prefill",
                   "ms": 0.8},
                  {"phase": "migrate", "t_ms": 1.1, "from_replica": 0,
                   "to_replica": 1},
                  {"phase": "retire", "t_ms": 2.0, "closed": "decode",
                   "ms": 0.5}],
              "summary": {}}
        trace, n, stitched = trace_export.chrome_trace([ev])
        assert n == 1 and stitched == 0
        by_name = {e["name"]: e for e in trace["traceEvents"]
                   if e["ph"] == "X"}
        assert by_name["queue"]["pid"] == 0
        assert by_name["prefill"]["pid"] == 0      # work the dead one did
        assert by_name["decode"]["pid"] == 1       # survivor's work
        # both replicas carry the request's track metadata
        meta_pids = {e["pid"] for e in trace["traceEvents"]
                     if e["ph"] == "M" and e["name"] == "thread_name"}
        assert meta_pids == {0, 1}

    def test_telemetry_report_folds_traces(self, jsonl, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import telemetry_report
        assert telemetry_report.main([jsonl, "--json"]) == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        ph = summary["trace_phases"]
        for k in ("queue_ms", "prefill_ms", "decode_ms",
                  "decode_ms_per_token", "wall_ms"):
            assert ph[k]["n"] == 2 and ph[k]["p50"] is not None
        tenants = summary["trace_tenants"]
        assert set(tenants) == {"acme", "bob"}
        assert tenants["acme"]["traces"] == 1
        # per-tenant ttft parsed from the registry snapshot through the
        # SAME prom grammar the /metrics exporter uses
        assert tenants["acme"]["ttft_p95"] is not None

    def test_report_renders_tables(self, jsonl, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import telemetry_report
        telemetry_report.main([jsonl])
        out = capsys.readouterr().out
        assert "Request phase" in out and "| Tenant |" in out
