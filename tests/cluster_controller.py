"""Active cluster controller for the ``serving-cluster`` CI gate's
controller-SIGKILL scenario (tools/ci.py gate_serving_cluster).

Runs ONE :class:`ClusterController` under a :class:`ControllerLease`
against an existing TCPStore and consumes gateway-style submissions
from the ``<prefix>/gate/req`` StoreQueue: each item is
``{"prompt": [...], "max_new_tokens": N, "key": idempotency-key}``;
the rid it admits under is acked back to ``<prefix>/gate/ack/<key>``
AFTER the durable journal write, so the gate can verify that a
duplicate idempotency key re-submitted through the standby (after this
process is SIGKILLed mid-churn) resolves to the SAME rid.

Faults ride ``PDTPU_FAULTS`` like the worker processes do — the gate
injects transient ``cluster.journal`` faults here, absorbed by the
controller's RetryPolicy.

The process never exits on its own: the gate SIGKILLs it mid-churn and
the in-gate standby takes over off the stale controller lease.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.environ["PDTPU_REPO"])

import numpy as np  # noqa: E402

from paddle_tpu import resilience as rs  # noqa: E402
from paddle_tpu.launch.store import TCPStore  # noqa: E402
from paddle_tpu.resilience.retry import RetryPolicy  # noqa: E402
from paddle_tpu.serving.cluster import (ClusterController,  # noqa: E402
                                        ControllerLease, StoreQueue)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True)
    ap.add_argument("--prefix", default="cluster")
    ap.add_argument("--lease-deadline-s", type=float, default=3.0)
    ap.add_argument("--worker-lease-deadline-s", type=float, default=6.0)
    args = ap.parse_args()

    rs.install_faults_from_env()
    store = TCPStore(args.store, is_master=False)
    lease = ControllerLease(store, prefix=args.prefix,
                            holder=f"ctl-sub-{os.getpid()}",
                            deadline_s=args.lease_deadline_s)
    ctl = ClusterController(
        store, prefix=args.prefix, lease=lease,
        lease_deadline_s=args.worker_lease_deadline_s,
        retry=RetryPolicy(max_attempts=5, backoff_s=0.01))
    req = StoreQueue(store, f"{args.prefix}/gate/req")
    print(json.dumps({"ready": True, "ctl_epoch": ctl.ctl_epoch}),
          flush=True)
    while True:
        for item in req.pop_all():
            rid = ctl.submit(
                np.asarray(item["prompt"], np.int32),
                max_new_tokens=int(item.get("max_new_tokens", 8)),
                idempotency_key=item.get("key"))
            if item.get("key") is not None:
                store.set(f"{args.prefix}/gate/ack/{item['key']}",
                          rid.encode())
        ctl.pump()
        time.sleep(0.01)


if __name__ == "__main__":
    main()
