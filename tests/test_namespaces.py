"""M16 namespace tests: static graph facade, utils, sparse, quantization,
vision, audio."""

import numpy as np
import pytest

import paddle_tpu as pt


class TestStatic:
    def test_program_guard_data_executor(self):
        from paddle_tpu import static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4])
            y = static.data("y", [None, 4])
            z = (x * 2 + y).sum(axis=1)
            loss = z.mean()
        exe = static.Executor()
        xv = np.ones((3, 4), "float32")
        yv = np.full((3, 4), 2.0, "float32")
        z_out, l_out = exe.run(main, feed={"x": xv, "y": yv},
                               fetch_list=[z, loss])
        np.testing.assert_allclose(z_out, np.full(3, 16.0), rtol=1e-6)
        assert abs(float(l_out) - 16.0) < 1e-5

    def test_executor_caches_compilation(self):
        from paddle_tpu import static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2])
            y = x.exp().sum()
        exe = static.Executor()
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")}, fetch_list=[y])
        n_cached = len(main._cache)
        exe.run(main, feed={"x": np.ones((2, 2), "float32")}, fetch_list=[y])
        assert len(main._cache) == n_cached  # same signature → cache hit
        exe.run(main, feed={"x": np.ones((5, 2), "float32")}, fetch_list=[y])
        assert len(main._cache) == n_cached + 1

    def test_static_nn_fc_and_apply(self):
        from paddle_tpu import static
        pt.seed(0)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 8])
            h = static.nn.fc(x, 16, activation="relu")
            out = static.apply(lambda v: v.mean(), h)
        r = static.Executor().run(
            main, feed={"x": np.random.randn(4, 8).astype("float32")},
            fetch_list=out)
        assert np.isfinite(r).all()

    def test_default_main_program(self):
        from paddle_tpu import static
        x = static.data("q", [2, 2])
        assert x.name in static.default_main_program().vars


class TestUtils:
    def test_run_check_and_unique_name(self, capsys):
        assert pt.utils.run_check()
        assert "successfully" in capsys.readouterr().out
        a = pt.utils.unique_name.generate("fc")
        b = pt.utils.unique_name.generate("fc")
        assert a == "fc_0" and b == "fc_1"
        with pt.utils.unique_name.guard():
            assert pt.utils.unique_name.generate("fc") == "fc_0"
        assert pt.utils.unique_name.generate("fc") == "fc_2"

    def test_deprecated_and_try_import(self):
        @pt.utils.deprecated(update_to="new_fn", since="0.1")
        def old_fn():
            return 42
        with pytest.warns(DeprecationWarning):
            assert old_fn() == 42
        assert pt.utils.try_import("math") is not None
        with pytest.raises(ImportError):
            pt.utils.try_import("definitely_not_installed_xyz")


class TestSparse:
    def test_coo_roundtrip_and_ops(self):
        import paddle_tpu.sparse as sp
        indices = np.array([[0, 1, 2], [1, 2, 0]])
        values = np.array([1.0, 2.0, 3.0], "float32")
        s = sp.sparse_coo_tensor(indices, values, (3, 3))
        assert s.nnz() == 3
        dense = np.asarray(s.to_dense())
        want = np.zeros((3, 3), "float32")
        want[0, 1], want[1, 2], want[2, 0] = 1, 2, 3
        np.testing.assert_array_equal(dense, want)
        # add
        s2 = sp.add(s, s)
        np.testing.assert_array_equal(np.asarray(s2.to_dense()), want * 2)
        # relu keeps structure
        neg = sp.sparse_coo_tensor(indices, -values, (3, 3))
        np.testing.assert_array_equal(np.asarray(sp.relu(neg).to_dense()),
                                      np.zeros((3, 3)))
        # spmm
        d = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(np.asarray(sp.matmul(s, d)), want @ d,
                                   rtol=1e-5)

    def test_csr_to_dense_and_coo(self):
        import paddle_tpu.sparse as sp
        # matrix [[1,0,2],[0,0,3]]
        s = sp.sparse_csr_tensor([0, 2, 3], [0, 2, 2], [1.0, 2.0, 3.0],
                                 (2, 3))
        want = np.array([[1, 0, 2], [0, 0, 3]], "float32")
        np.testing.assert_array_equal(np.asarray(s.to_dense()), want)
        coo = s.to_sparse_coo()
        np.testing.assert_array_equal(np.asarray(coo.to_dense()), want)

    def test_masked_matmul(self):
        import paddle_tpu.sparse as sp
        x = np.random.randn(3, 4).astype("float32")
        y = np.random.randn(4, 3).astype("float32")
        mask = sp.sparse_coo_tensor([[0, 2], [1, 0]], [1.0, 1.0], (3, 3))
        out = sp.masked_matmul(x, y, mask)
        full = x @ y
        dense = np.asarray(out.to_dense())
        np.testing.assert_allclose(dense[0, 1], full[0, 1], rtol=1e-5)
        np.testing.assert_allclose(dense[2, 0], full[2, 0], rtol=1e-5)
        assert dense[1, 1] == 0


class TestQuantization:
    def test_fake_quant_close_and_ste_grad(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.quantization import FakeQuanterWithAbsMax
        # seeded: the unseeded global stream made this order-dependent —
        # ~1% of draws put a SECOND element on a rounding/clip tie where
        # the STE subgradient is 0.5 (only the argmax was excluded below)
        x = np.random.RandomState(0).randn(32).astype("float32")
        fq = FakeQuanterWithAbsMax(bits=8)
        out = np.asarray(fq(jnp.asarray(x)))
        assert np.abs(out - x).max() < np.abs(x).max() / 100  # 8-bit error
        g = np.asarray(jax.grad(lambda v: (fq(v) ** 2).sum())(jnp.asarray(x)))
        # STE: grad flows everywhere; the abs-max element sits exactly on
        # the clip boundary where jax's min/max gradient is 0.5 at ties —
        # exclude it from the exact comparison
        keep = np.arange(len(x)) != np.abs(x).argmax()
        np.testing.assert_allclose(g[keep], (2 * out)[keep], rtol=1e-4,
                                   atol=1e-5)
        assert np.isfinite(g).all()

    def test_qat_quantize_and_train(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.quantization import QAT, QuantConfig
        from paddle_tpu.nn.layer import functional_call, raw_params
        from paddle_tpu.optimizer import AdamW

        pt.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        qat = QAT(QuantConfig(weight_bits=8))
        model = qat.quantize(model)
        x = jnp.asarray(np.random.randn(16, 8).astype("float32"))
        y = jnp.asarray(np.random.randn(16, 2).astype("float32"))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        params = raw_params(model)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            def loss(p):
                return ((functional_call(model, p, x) - y) ** 2).mean()
            l, g = jax.value_and_grad(loss)(params)
            params, state = opt.apply(g, state, params)
            return params, state, l

        l0 = None
        for _ in range(25):
            params, state, l = step(params, state)
            if l0 is None:
                l0 = float(l)
        assert float(l) < l0 * 0.7

        # write trained params back, then convert → int8 weights materialized
        for k, v in params.items():
            model._assign_by_path(k, v)
        qat.convert(model)
        lin = model[0]
        assert hasattr(lin, "weight_quant") and lin.weight_quant.dtype == jnp.int8


class TestQuantFixes:
    def test_qat_wraps_attribute_access_models(self):
        """The wrapper must be visible through self.fc, not just
        _sub_layers — models call sublayers by attribute."""
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.quantization import QAT, _QuantWrapper

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        pt.seed(0)
        m = QAT().quantize(M())
        assert isinstance(m.fc, _QuantWrapper)
        x = jnp.asarray(np.random.randn(2, 4).astype("float32"))
        out_model = np.asarray(m(x))
        out_wrapper = np.asarray(m._sub_layers["fc"](x))
        np.testing.assert_allclose(out_model, out_wrapper, rtol=1e-6)

    def test_quantize_absmax_wide_bits(self):
        from paddle_tpu.quantization import quantize_absmax, dequantize
        import jax.numpy as jnp
        x = np.random.randn(64).astype("float32") * 10
        q, s = quantize_absmax(jnp.asarray(x), bits=16)
        assert q.dtype == jnp.int16
        np.testing.assert_allclose(np.asarray(dequantize(q, s)), x,
                                   atol=np.abs(x).max() / 30000)

    def test_ptq_observes_then_converts(self):
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.quantization import PTQ

        pt.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        ptq = PTQ()
        m = ptq.quantize(m)
        x = jnp.asarray(np.random.randn(16, 4).astype("float32") * 3)
        before = np.asarray(m(x))  # observation pass is TRANSPARENT
        ref = np.asarray(m(x))
        np.testing.assert_allclose(before, ref, rtol=1e-6)
        ptq.convert(m)
        lin = m[0]
        assert hasattr(lin, "act_scale") and float(lin.act_scale) > 0
        assert hasattr(lin, "weight_quant")
        after = np.asarray(m(x))
        np.testing.assert_allclose(after, before, atol=0.1)  # 8-bit weights


class TestVision:
    def test_transforms_pipeline(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.rand(40, 60, 3) * 255).astype("uint8")
        pipe = T.Compose([T.Resize(32), T.CenterCrop(32), T.ToTensor(),
                          T.Normalize([0.5] * 3, [0.5] * 3)])
        out = pipe(img)
        assert out.shape == (3, 32, 32)
        assert out.dtype == np.float32 and np.abs(out).max() <= 1.0 + 1e-6

    def test_resize_shorter_edge(self):
        from paddle_tpu.vision.transforms import Resize
        img = np.zeros((40, 80, 3), "float32")
        out = Resize(20)(img)
        assert out.shape == (20, 40, 3)

    def test_lenet_and_resnet18_train_step(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.vision.models import LeNet, resnet18
        from paddle_tpu.nn.layer import functional_call, raw_params

        pt.seed(0)
        m = LeNet()
        x = jnp.zeros((2, 1, 28, 28))
        assert m(x).shape == (2, 10)

        r = resnet18(num_classes=10)
        x = jnp.zeros((1, 3, 32, 32))
        out = r(x)
        assert out.shape == (1, 10)
        p = raw_params(r)
        g = jax.grad(lambda p: functional_call(r, p, x, training=True).sum())(p)
        assert all(np.isfinite(np.asarray(v)).all() for v in g.values())

    def test_random_dataset_with_loader(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.vision.datasets import RandomDataset
        from paddle_tpu.vision import transforms as T
        ds = RandomDataset(num_samples=8, image_shape=(3, 8, 8))
        dl = DataLoader(ds, batch_size=4)
        batches = list(dl)
        assert batches[0][0].shape == (4, 3, 8, 8)
        assert batches[0][1].dtype == np.int64


class TestAudio:
    def test_stft_parseval_and_mel(self):
        import paddle_tpu.audio as audio
        t = np.linspace(0, 1, 4000, dtype="float32")
        x = np.sin(2 * np.pi * 440 * t)
        spec = np.asarray(audio.spectrogram(x, n_fft=256, hop_length=128))
        assert spec.shape[0] == 129
        # peak bin should be near 440Hz: bin = 440/ (4000/2) * 128
        peak = spec.mean(-1).argmax()
        want_bin = round(440 / (4000 / 2) * 128)
        assert abs(int(peak) - want_bin) <= 1
        mel = audio.MelSpectrogram(sr=4000, n_fft=256, n_mels=20)(x)
        assert mel.shape[0] == 20
        assert np.isfinite(np.asarray(mel)).all()


class TestVisionZoo:
    """New model families (reference python/paddle/vision/models/):
    forward shape + finite grads on tiny inputs."""

    def _check(self, model, in_shape=(1, 3, 64, 64), n_cls=10):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nn.layer import functional_call, raw_params
        x = jnp.ones(in_shape, jnp.float32)
        out = model(x)
        assert out.shape == (in_shape[0], n_cls)
        p = raw_params(model)
        g = jax.grad(
            lambda p: functional_call(model, p, x, training=True).sum())(p)
        leaves = jax.tree_util.tree_leaves(g)
        assert leaves and all(np.isfinite(np.asarray(v)).all()
                              for v in leaves)

    def test_vgg11_bn(self):
        from paddle_tpu.vision.models import vgg11
        pt.seed(0)
        self._check(vgg11(batch_norm=True, num_classes=10))

    def test_alexnet(self):
        from paddle_tpu.vision.models import alexnet
        pt.seed(0)
        self._check(alexnet(num_classes=10))

    def test_squeezenet(self):
        from paddle_tpu.vision.models import squeezenet1_1
        pt.seed(0)
        self._check(squeezenet1_1(num_classes=10))

    def test_mobilenet_v1_v2(self):
        from paddle_tpu.vision.models import mobilenet_v1, mobilenet_v2
        pt.seed(0)
        self._check(mobilenet_v1(scale=0.25, num_classes=10))
        self._check(mobilenet_v2(scale=0.25, num_classes=10))

    def test_densenet121(self):
        from paddle_tpu.vision.models import densenet121
        pt.seed(0)
        self._check(densenet121(num_classes=10))

    def test_relu6_hardswish(self):
        import jax.numpy as jnp
        from paddle_tpu.nn import functional as F
        x = jnp.array([-4.0, -1.0, 0.0, 3.0, 7.0])
        np.testing.assert_allclose(F.relu6(x), [0, 0, 0, 3, 6])
        np.testing.assert_allclose(
            F.hardswish(x), x * np.clip(np.asarray(x) + 3, 0, 6) / 6)


class TestVersionAndModes:
    def test_version_module(self):
        assert pt.version.full_version == pt.__version__
        assert pt.version.cuda() is False

    def test_static_mode_toggles(self):
        assert pt.in_dynamic_mode()
        pt.enable_static()
        try:
            assert not pt.in_dynamic_mode()
        finally:
            pt.disable_static()
        assert pt.in_dynamic_mode()


class TestVisionModelTail:
    """Round-2 vision families (reference:
    python/paddle/vision/models/{resnet,shufflenetv2,googlenet}.py)."""

    def _run(self, model, size=64):
        import jax.numpy as jnp
        x = jnp.zeros((1, 3, size, size))
        out = model.eval()(x)
        assert out.shape == (1, 10)
        return model

    def test_resnext_and_wide_resnet(self):
        from paddle_tpu.vision.models import (resnext50_32x4d,
                                              wide_resnet50_2)
        pt.seed(0)
        rx = self._run(resnext50_32x4d(num_classes=10))
        # grouped 3x3: weight in-channel dim is width/groups
        w = rx.layer1[0].conv2.weight
        assert w.shape[1] * 32 == w.shape[0]
        wr = self._run(wide_resnet50_2(num_classes=10))
        assert wr.layer1[0].conv2.weight.shape[0] == 128  # 2x width

    def test_shufflenet_v2(self):
        from paddle_tpu.vision.models import shufflenet_v2_x0_5
        pt.seed(0)
        m = self._run(shufflenet_v2_x0_5(num_classes=10))
        n = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert n < 1.5e6  # x0.5 is the sub-1.5M-param preset

    def test_googlenet(self):
        from paddle_tpu.vision.models import googlenet
        pt.seed(0)
        m = self._run(googlenet(num_classes=10))
        n = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert 5e6 < n < 8e6  # inception-v1 backbone scale

    def test_resnext_needs_bottleneck(self):
        import pytest
        from paddle_tpu.vision.models import ResNet
        with pytest.raises(ValueError, match="bottleneck"):
            ResNet(18, groups=32, width_per_group=4)


class TestTopLevelParityRound2:
    def test_places_and_tensor_aliases(self):
        import jax.numpy as jnp
        assert repr(pt.CPUPlace()) == "CPUPlace()"
        assert "Place(0)" in repr(pt.CUDAPlace(0))   # accelerator = TPU
        t = pt.tensor([1.0, 2.0])
        assert pt.is_tensor(t) and not pt.is_tensor("x")
        assert pt.iinfo("int32").max == 2**31 - 1
        assert pt.finfo("float32").eps > 0

    def test_rng_state_roundtrip(self):
        pt.seed(7)
        _ = pt.randn([3])
        state = pt.get_rng_state()
        a = np.asarray(pt.randn([4]))
        pt.set_rng_state(state)
        b = np.asarray(pt.randn([4]))
        np.testing.assert_array_equal(a, b)

    def test_grad_enabled_flag(self):
        assert pt.is_grad_enabled()
        with pt.no_grad():
            assert not pt.is_grad_enabled()
        with pt.set_grad_enabled(False):
            assert not pt.is_grad_enabled()
        assert pt.is_grad_enabled()

    def test_incubate_top_level(self):
        from paddle_tpu import incubate
        assert hasattr(incubate, "LookAhead")
        assert hasattr(incubate, "ModelAverage")


class TestStaticRound2:
    def test_gradients_and_append_backward(self):
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = prog.data("x", (3,), "float32")
            y = prog.data("y", (3,), "float32")
            z = (x * y + x.exp()).sum()
            gx, gy = static.gradients(z, [x, y])
        exe = static.Executor()
        xv = np.array([0.1, 0.2, 0.3], np.float32)
        yv = np.array([1.0, 2.0, 3.0], np.float32)
        _, g1, g2 = exe.run(prog, feed={"x": xv, "y": yv},
                            fetch_list=[z, gx, gy])
        np.testing.assert_allclose(g1, yv + np.exp(xv), rtol=1e-5)
        np.testing.assert_allclose(g2, xv, rtol=1e-6)
        pairs = static.append_backward(z)
        assert [v.name for v, _ in pairs] == ["x", "y"]

    def test_scope_guard(self):
        from paddle_tpu import static
        sc = static.Scope()
        with static.scope_guard(sc):
            static.global_scope().set_var("a", 1)
            assert static.global_scope().find_var("a") == 1
        assert static.global_scope().find_var("a") is None

    def test_save_load_inference_model(self, tmp_path):
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = prog.data("x", (4,), "float32")
            out = (x * 2.0 + 1.0).sum()
        exe = static.Executor()
        path = str(tmp_path / "inf")
        static.save_inference_model(path, [x], [out], exe)
        prog2, feeds, fetches = static.load_inference_model(path, exe)
        xv = np.arange(4, dtype=np.float32)
        ref = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        got = exe.run(prog2, feed={"x": xv}, fetch_list=fetches)
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)

    def test_gradients_wrt_intermediate(self):
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = prog.data("x", (3,), "float32")
            h = x * 2.0
            z = (h * h).sum()
            (gh,) = static.gradients(z, [h])
        exe = static.Executor()
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        (g,) = exe.run(prog, feed={"x": xv}, fetch_list=[gh])
        np.testing.assert_allclose(g, 2 * (2 * xv), rtol=1e-6)  # dz/dh = 2h

    def test_set_grad_enabled_imperative(self):
        pt.set_grad_enabled(False)
        assert not pt.is_grad_enabled()
        pt.set_grad_enabled(True)
        assert pt.is_grad_enabled()

    def test_place_isinstance_and_to_tensor_bridge(self):
        t = pt.to_tensor([1.0, 2.0], place=pt.CPUPlace())
        assert pt.is_tensor(t)
        assert isinstance(pt.CUDAPlace(0), pt.CUDAPlace)
        assert isinstance(pt.CPUPlace(), pt.CPUPlace)
        t2 = pt.tensor([3.0], place=pt.CUDAPlace(0))
        assert pt.is_tensor(t2)


class TestVisionModelsTail3:
    """Round-3 model zoo tail (reference:
    python/paddle/vision/models/{mobilenetv3,inceptionv3,lenet}.py)."""

    _check = TestVisionZoo.__dict__["_check"]

    def test_mobilenet_v3(self):
        from paddle_tpu.vision.models import (mobilenet_v3_large,
                                              mobilenet_v3_small)
        pt.seed(0)
        self._check(mobilenet_v3_small(scale=0.5, num_classes=10))
        self._check(mobilenet_v3_large(scale=0.35, num_classes=10))

    def test_inception_v3(self):
        from paddle_tpu.vision.models import inception_v3
        pt.seed(0)
        self._check(inception_v3(num_classes=10), in_shape=(1, 3, 96, 96))

    def test_lenet_factory(self):
        import jax.numpy as jnp
        from paddle_tpu.vision.models import lenet
        pt.seed(0)
        m = lenet(num_classes=10)
        assert m(jnp.ones((2, 1, 28, 28))).shape == (2, 10)


class TestOpsOnStaticVars:
    """Round-3: dynamic paddle_tpu.ops / nn.functional callables accept
    static.Var placeholders directly (VERDICT r2 weak #6 — previously
    static-graph code had to be rewritten to Var methods/static.apply)."""

    def test_dynamic_ops_record_on_vars(self):
        import numpy as np
        import paddle_tpu.nn.functional as F
        from paddle_tpu import static

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", (4, 8), "float32")
            h = pt.add(pt.matmul(x, pt.ones((8, 3))), 0.0)  # ufunc path
            h = F.relu(h)                                   # custom_jvp path
            h = F.softmax(h, axis=-1)
            s = pt.sum(h, axis=-1)
        exe = static.Executor()
        xv = np.random.default_rng(0).standard_normal((4, 8)) \
            .astype("float32")
        out = exe.run(prog, feed={"x": xv}, fetch_list=[s])[0]
        np.testing.assert_allclose(out, np.ones(4, np.float32), rtol=1e-5)

    def test_gradients_through_dynamic_ops(self):
        import numpy as np
        from paddle_tpu import static

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", (3, 3), "float32")
            y = pt.sum(pt.tanh(x) * 2.0)
            (gx,) = static.gradients([y], [x])
        exe = static.Executor()
        xv = np.random.default_rng(1).standard_normal((3, 3)) \
            .astype("float32")
        g = exe.run(prog, feed={"x": xv}, fetch_list=[gx])[0]
        np.testing.assert_allclose(g, 2.0 * (1 - np.tanh(xv) ** 2),
                                   rtol=1e-5)

    def test_eager_calls_unaffected(self):
        import jax.numpy as jnp
        import numpy as np
        out = pt.add(jnp.ones(3), jnp.ones(3))
        np.testing.assert_allclose(np.asarray(out), 2.0)
