"""Batched multi-LoRA serving (docs/SERVING.md "Multi-LoRA").

The load-bearing guarantees:

- the grouped BGMV kernel (ops/pallas/lora_matmul.py, run in the Pallas
  interpreter here) matches its XLA gather+einsum contract
  (``incubate.nn.functional._lora_bgmv_ref``) across mixed adapter ids,
  ranks, and dtypes — with adapter 0 an EXACT no-op;
- an engine serving adapter ``k`` produces greedy outputs
  token-identical to a merged-weight (``W + B_k A_k``) reference model,
  across prefix-cache hits, int8 KV pools, preempt→swap→restore,
  speculative decoding, TP=2, DP evacuation, and the disaggregated
  handoff — while base requests stay bitwise identical to a LoRA-less
  engine (slot 0's zero stacks);
- adapter churn (load / hot-load / evict) never recompiles, and the
  lifecycle errors are typed (UnknownAdapter at admission, AdapterInUse
  on a refcount-held evict).
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import resilience as rs
from paddle_tpu import serving
from paddle_tpu.serving import LoRAPool, merge_adapter, random_adapter
from paddle_tpu.serving.errors import AdapterInUse, UnknownAdapter

R = np.random.default_rng(0)


def _prompt(n):
    return R.integers(0, 256, size=n).astype(np.int32)


def _tiny():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


def _tiny_gpt():
    from paddle_tpu.models.gpt import gpt
    pt.seed(0)
    return gpt("tiny")


def _engine(model=None, lora=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(model if model is not None else _tiny(),
                          lora=lora, **kw)


def _weights(model, rank=8, seed=7, scale=0.05, projs=None):
    return random_adapter(model, rank=rank,
                          rng=np.random.default_rng(seed), scale=scale,
                          projs=projs)


def _merged_ref(weights, prompt, max_new, builder=_tiny):
    """Greedy generate() on a fresh model with the adapter merged in."""
    m = builder()
    merge_adapter(m, weights)
    out = m.generate(jnp.asarray(np.asarray(prompt))[None],
                     max_new_tokens=max_new, temperature=0.0)
    return list(np.asarray(out)[0, len(prompt):])


# ---------------------------------------------------------------------------
# pool lifecycle
# ---------------------------------------------------------------------------

class TestLoRAPool:
    def test_slots_and_registry(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=2, rank=8)
        assert pool.active_adapters == 0
        s1 = pool.load("a", _weights(model))
        s2 = pool.load("b", _weights(model, seed=8))
        assert {s1, s2} == {1, 2}          # slot 0 stays the base no-op
        assert pool.adapters() == {"a": s1, "b": s2}
        assert pool.slot_of("a") == s1

    def test_unknown_adapter_typed(self):
        pool = LoRAPool(_tiny(), max_adapters=1, rank=8)
        with pytest.raises(UnknownAdapter, match="not loaded"):
            pool.slot_of("ghost")

    def test_pool_full(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        pool.load("a", _weights(model))
        with pytest.raises(ValueError, match="full"):
            pool.load("b", _weights(model))

    def test_reload_keeps_slot(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        s = pool.load("a", _weights(model))
        assert pool.load("a", _weights(model, seed=9)) == s

    def test_evict_in_use_typed_then_ok(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        pool.load("a", _weights(model))
        pool.acquire("a", "req-x")
        pool.acquire("a", "req-x")          # id-keyed: idempotent
        assert pool.refcount("a") == 1
        with pytest.raises(AdapterInUse, match="live"):
            pool.evict("a")
        pool.release("a", "req-x")
        pool.evict("a")
        assert not pool.has("a") and pool.active_adapters == 0
        # the freed slot is reusable
        assert pool.load("b", _weights(model)) == 1

    def test_bad_shapes_rejected(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        w = _weights(model)
        a, b = w[0]["self_attn.q_proj"]
        w[0]["self_attn.q_proj"] = (a[:, :4], b)   # wrong rank
        with pytest.raises(ValueError, match="do not match"):
            pool.load("a", w)

    def test_failed_load_leaks_nothing(self):
        # a mid-load shape failure must neither consume the popped slot
        # nor half-overwrite a resident adapter (load validates every
        # row BEFORE mutating pool state)
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        bad = _weights(model)
        a, b = bad[1]["self_attn.q_proj"]          # fail at layer 1:
        bad[1]["self_attn.q_proj"] = (a[:, :4], b)  # layer 0 was valid
        with pytest.raises(ValueError, match="do not match"):
            pool.load("a", bad)
        assert pool.active_adapters == 0
        good = _weights(model, seed=9)
        slot = pool.load("a", good)                 # slot NOT leaked
        snap = np.array(pool._host[0]["self_attn.q_proj"]["a"][slot])
        bad2 = _weights(model, seed=10)
        a2, b2 = bad2[1]["self_attn.q_proj"]
        bad2[1]["self_attn.q_proj"] = (a2[:, :4], b2)
        with pytest.raises(ValueError, match="do not match"):
            pool.load("a", bad2)                    # failed hot-reload
        np.testing.assert_array_equal(               # old rows intact
            pool._host[0]["self_attn.q_proj"]["a"][slot], snap)

    def test_unknown_projection_keys_rejected(self):
        # PEFT-style short keys ('q_proj') silently missing every pool
        # target would load an all-zero adapter that serves BASE
        # outputs under the tenant's name — reject loudly
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        w = _weights(model)
        a, b = w[0].pop("self_attn.q_proj")
        w[0]["q_proj"] = (a, b)
        with pytest.raises(ValueError, match="unknown projection"):
            pool.load("a", w)
        assert pool.active_adapters == 0

    def test_acquire_unknown_adapter_typed(self):
        # a blind ref on a non-resident name would let its slot be
        # zeroed or reused under the request (disagg adoption window)
        pool = LoRAPool(_tiny(), max_adapters=1, rank=8)
        with pytest.raises(UnknownAdapter, match="not loaded"):
            pool.acquire("ghost", "rid-1")

    def test_geometry_validation_at_engine(self):
        pool = LoRAPool(_tiny(), max_adapters=1, rank=8)
        with pytest.raises(ValueError, match="geometry"):
            _engine(model=_tiny_gpt(), lora=pool)

    def test_quantized_model_rejected(self):
        model = _tiny()
        from paddle_tpu.nn.quant import quantize_linears
        quantize_linears(model, algo="weight_only_int8")
        with pytest.raises(ValueError, match="quantized"):
            LoRAPool(model, max_adapters=1, rank=8)


# ---------------------------------------------------------------------------
# grouped BGMV kernel vs the XLA contract (interpret mode)
# ---------------------------------------------------------------------------

class TestGroupedBGMV:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("rank", [8, 16, 64])
    def test_kernel_matches_contract(self, dtype, rank):
        from paddle_tpu.incubate.nn.functional import _lora_bgmv_ref
        from paddle_tpu.ops.pallas.lora_matmul import grouped_bgmv
        rng = np.random.default_rng(3)
        B, C, H, O, N = 4, 16, 256, 384, 5
        x = jnp.asarray(rng.normal(size=(B, C, H)), dtype)
        a = jnp.asarray(rng.normal(size=(N, H, rank)) * 0.05, dtype)
        b = jnp.asarray(rng.normal(size=(N, rank, O)) * 0.05, dtype)
        a = a.at[0].set(0.0)
        b = b.at[0].set(0.0)
        idx = jnp.asarray(np.array([0, 3, 1, 3], np.int32))  # mixed ids
        got = np.asarray(grouped_bgmv(x, a, b, idx, interpret=True),
                         np.float32)
        ref = np.asarray(_lora_bgmv_ref(x, a, b, idx), np.float32)
        np.testing.assert_allclose(got, ref, rtol=0, atol=0)
        # adapter 0 is the EXACT no-op: all-zero delta, bit for bit
        assert (got[0] == 0.0).all()

    def test_expand_stripes_match(self):
        from paddle_tpu.incubate.nn.functional import _lora_bgmv_ref
        from paddle_tpu.ops.pallas.lora_matmul import grouped_bgmv
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(2, 8, 128)), jnp.float32)
        a = jnp.asarray(rng.normal(size=(3, 128, 16)), jnp.float32)
        b = jnp.asarray(rng.normal(size=(3, 16, 384)), jnp.float32)
        idx = jnp.asarray(np.array([2, 1], np.int32))
        got = grouped_bgmv(x, a, b, idx, block_o=128, interpret=True)
        ref = _lora_bgmv_ref(x, a, b, idx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=0, atol=0)

    def test_dispatch_declines_off_tpu(self):
        # CPU: the incubate entry must take the XLA composition
        from paddle_tpu.incubate.nn import functional as IF
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(2, 4, 64)), jnp.float32)
        a = jnp.asarray(rng.normal(size=(2, 64, 8)), jnp.float32)
        b = jnp.asarray(rng.normal(size=(2, 8, 64)), jnp.float32)
        idx = jnp.asarray(np.array([1, 0], np.int32))
        out = IF.lora_bgmv(x, a, b, idx)
        ref = IF._lora_bgmv_ref(x, a, b, idx)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# engine identity: batched adapters vs merged-weight references
# ---------------------------------------------------------------------------

class TestEngineIdentity:
    def _pooled_engine(self, n_adapters=2, builder=_tiny, **kw):
        model = builder()
        pool = LoRAPool(model, max_adapters=n_adapters, rank=8)
        ws = {}
        for i in range(n_adapters):
            name = f"ad{i}"
            ws[name] = _weights(model, seed=20 + i)
            pool.load(name, ws[name])
        return _engine(model=model, lora=pool, **kw), pool, ws

    def test_base_bitwise_identical_to_plain_engine(self):
        prompts = [_prompt(5), _prompt(17)]
        eng, _, _ = self._pooled_engine()
        rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        eng.warmup()
        outs = eng.run()
        plain = _engine().warmup()
        prids = [plain.add_request(p, max_new_tokens=6) for p in prompts]
        pouts = plain.run()
        assert [outs[r] for r in rids] == [pouts[r] for r in prids]

    def test_mixed_batch_matches_merged_references(self):
        eng, pool, ws = self._pooled_engine(max_batch=4)
        eng.warmup()
        prompts = [_prompt(n) for n in (5, 17, 9, 26)]
        mix = [None, "ad0", "ad1", "ad0"]
        rids = [eng.add_request(p, max_new_tokens=6, adapter=ad)
                for p, ad in zip(prompts, mix)]
        outs = eng.run()
        for p, ad, rid in zip(prompts, mix, rids):
            if ad is None:
                m = _tiny()
                ref = list(np.asarray(m.generate(
                    jnp.asarray(p)[None], max_new_tokens=6,
                    temperature=0.0))[0, len(p):])
            else:
                ref = _merged_ref(ws[ad], p, 6)
            assert outs[rid] == ref, f"adapter {ad} diverged"
        assert eng.kv_blocks_used == 0

    def test_gpt_family(self):
        eng, pool, ws = self._pooled_engine(builder=_tiny_gpt)
        eng.warmup()
        p = _prompt(9)
        rid = eng.add_request(p, max_new_tokens=6, adapter="ad0")
        outs = eng.run()
        assert outs[rid] == _merged_ref(ws["ad0"], p, 6,
                                        builder=_tiny_gpt)

    def test_prefix_cache_hits_with_adapters(self):
        """Two tenants share a prompt prefix: the KV pages are
        adapter-INDEPENDENT up to the divergence point only if the
        adapter is the same — different adapters write different KV, so
        identity must hold precisely because each request's pages are
        its own (prefix sharing keys on content, and adapter deltas
        change the content hash's PAYLOAD, not the hash: the test pins
        that sharing never crosses adapters incorrectly)."""
        eng, pool, ws = self._pooled_engine(max_batch=2)
        eng.warmup()
        p = _prompt(16)            # 2 full pages: registered at retire
        r1 = eng.add_request(p, max_new_tokens=5, adapter="ad0")
        o1 = eng.run()[r1]
        # same prompt, same adapter → prefix hit, identical outputs
        r2 = eng.add_request(p, max_new_tokens=5, adapter="ad0")
        o2 = eng.run()[r2]
        assert o1 == o2 == _merged_ref(ws["ad0"], p, 5)
        assert eng.prefix_stats()["hits"] > 0

    def test_adapters_change_kv_so_prefix_sharing_must_not_cross(self):
        """The sharp edge of prefix caching under multi-LoRA: adapter
        deltas change K/V at every position, so a page prefilled under
        adapter A must never be borrowed by a request on adapter B (or
        the base model) however identical their tokens.  The adapter
        name SALTS the chained page digests (scheduler.submit →
        PrefixCache.page_keys(salt=)), so colliding prompts on
        different adapters key disjoint cache entries — this test
        caught the unsalted version serving adapter B from A's pages."""
        eng, pool, ws = self._pooled_engine(max_batch=2)
        eng.warmup()
        p = _prompt(16)
        r1 = eng.add_request(p, max_new_tokens=5, adapter="ad0")
        o1 = eng.run()[r1]
        r2 = eng.add_request(p, max_new_tokens=5, adapter="ad1")
        o2 = eng.run()[r2]
        assert o1 == _merged_ref(ws["ad0"], p, 5)
        assert o2 == _merged_ref(ws["ad1"], p, 5)

    def test_int8_kv_pool(self):
        eng, pool, ws = self._pooled_engine(kv_cache_dtype="int8")
        eng.warmup()
        p = _prompt(11)
        rid = eng.add_request(p, max_new_tokens=6, adapter="ad1")
        outs = eng.run()
        m = _tiny()
        merge_adapter(m, ws["ad1"])
        ref = list(np.asarray(m.generate(
            jnp.asarray(p)[None], max_new_tokens=6, temperature=0.0,
            kv_cache_dtype="int8"))[0, len(p):])
        assert outs[rid] == ref

    def test_preempt_swap_restore(self):
        eng, pool, ws = self._pooled_engine(max_batch=2)
        eng.warmup()
        p = _prompt(9)
        rid = eng.add_request(p, max_new_tokens=8, adapter="ad0")
        eng.step(); eng.step(); eng.step()
        assert eng.preempt(rid)
        # pdtpu-lint: disable=lock-discipline — single-threaded test
        assert eng._states[rid].preempts == 1
        outs = eng.run()
        assert outs[rid] == _merged_ref(ws["ad0"], p, 8)
        assert pool.refcount("ad0") == 0   # released at retire
        assert eng.kv_blocks_used == 0

    def test_spec_decode_composes(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        ws = _weights(model, seed=33)
        pool.load("a", ws)
        eng = _engine(model=model, lora=pool, spec_decode=True,
                      max_seq_len=64).warmup()
        motif = _prompt(6)
        p = np.tile(motif, 3)
        rid = eng.add_request(p, max_new_tokens=10, adapter="a")
        outs = eng.run()
        assert outs[rid] == _merged_ref(ws, p, 10)
        assert eng.kv_blocks_used == 0

    def test_hot_load_and_churn_zero_recompiles(self):
        from paddle_tpu import observability as obs
        tel = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        try:
            model = _tiny()
            pool = LoRAPool(model, max_adapters=2, rank=8)
            wa = _weights(model, seed=40)
            pool.load("a", wa)
            eng = _engine(model=model, lora=pool,
                          max_batch=2).warmup()
            c0 = tel.sentinel.compiles()
            pa, pb = _prompt(9), _prompt(5)
            eng.add_request(pa, max_new_tokens=6, adapter="a")
            eng.step(); eng.step()
            wb = _weights(model, seed=41)
            pool.load("b", wb)          # hot-load mid-churn
            r1 = eng.add_request(pb, max_new_tokens=6, adapter="b")
            outs = eng.run()
            pool.evict("a")
            eng.add_request(_prompt(7), max_new_tokens=4, adapter="b")
            outs.update(eng.run())
            assert tel.sentinel.compiles() - c0 == 0
            assert eng._step_fn._cache_size() == 1
            assert outs[r1] == _merged_ref(wb, pb, 6)
        finally:
            obs.disable()

    def test_unknown_adapter_typed_at_add_request(self):
        eng, pool, _ = self._pooled_engine()
        with pytest.raises(UnknownAdapter, match="not loaded"):
            eng.add_request(_prompt(5), adapter="ghost")
        # engine without a pool: also typed
        with pytest.raises(UnknownAdapter, match="no LoRA pool"):
            _engine().add_request(_prompt(5), adapter="ad0")

    def test_eviction_blocked_by_live_request(self):
        eng, pool, _ = self._pooled_engine()
        eng.warmup()
        eng.add_request(_prompt(9), max_new_tokens=8, adapter="ad0")
        eng.step()
        with pytest.raises(AdapterInUse):
            pool.evict("ad0")
        eng.run()
        pool.evict("ad0")                  # drained: fine


# ---------------------------------------------------------------------------
# front door tenancy
# ---------------------------------------------------------------------------

class TestFrontDoorTenancy:
    def test_tenant_policy_maps_adapter(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        ws = _weights(model, seed=50)
        pool.load("fr-legal", ws)
        eng = _engine(model=model, lora=pool).warmup()
        door = serving.FrontDoor(eng, policies={
            "acme": serving.TenantPolicy(adapter="fr-legal")})
        p = _prompt(9)
        adm = door.submit(p, tenant="acme", max_new_tokens=6)
        assert adm.admitted
        outs = door.run()
        assert outs[adm.request_id] == _merged_ref(ws, p, 6)

    def test_explicit_adapter_overrides_policy(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=2, rank=8)
        wa, wb = _weights(model, seed=51), _weights(model, seed=52)
        pool.load("a", wa)
        pool.load("b", wb)
        eng = _engine(model=model, lora=pool).warmup()
        door = serving.FrontDoor(eng, policies={
            "t": serving.TenantPolicy(adapter="a")})
        p = _prompt(7)
        adm = door.submit(p, tenant="t", max_new_tokens=5, adapter="b")
        outs = door.run()
        assert outs[adm.request_id] == _merged_ref(wb, p, 5)

    def test_unknown_mapping_typed_at_submit(self):
        eng = _engine().warmup()
        door = serving.FrontDoor(eng, policies={
            "bad": serving.TenantPolicy(adapter="ghost")})
        with pytest.raises(UnknownAdapter):
            door.submit(_prompt(5), tenant="bad")

    def test_admitted_request_pins_adapter_until_retire(self):
        # an admitted=True answer is a promise: the adapter cannot be
        # evicted out from under a request the door still holds (the
        # door acquires the same id-keyed reference the engine takes
        # over at add_request), so pump never sheds a vetted request
        # on a vanished adapter
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        ws = _weights(model, seed=53)
        pool.load("pinned", ws)
        eng = _engine(model=model, lora=pool).warmup()
        door = serving.FrontDoor(eng, policies={
            "t": serving.TenantPolicy(adapter="pinned")})
        adms = [door.submit(_prompt(5 + i), tenant="t",
                            max_new_tokens=4) for i in range(3)]
        assert all(a.admitted for a in adms)
        with pytest.raises(AdapterInUse, match="live"):
            pool.evict("pinned")            # queued + staged requests
        outs = door.run()
        assert len(outs) == 3
        pool.evict("pinned")                # all retired: refs cleared

    def test_queuefull_requeue_keeps_adapter_pinned(self):
        # the engine's transient QueueFull at pump releases the shared
        # id-keyed ref on its way out of add_request; the door must
        # re-take it when it re-queues the pending, or the admitted
        # request loses its evict protection while waiting at the door
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        ws = _weights(model, seed=54)
        pool.load("pinned", ws)
        # max_queue < max_batch: _engine_room says feed, the engine's
        # own bound answers QueueFull -> door re-queues the pending
        eng = _engine(model=model, lora=pool, max_queue=1).warmup()
        door = serving.FrontDoor(eng, policies={
            "t": serving.TenantPolicy(adapter="pinned")})
        adms = [door.submit(_prompt(5 + i), tenant="t",
                            max_new_tokens=3) for i in range(3)]
        assert all(a.admitted for a in adms)
        assert door._total_queued() >= 1    # at least one bounced back
        with pytest.raises(AdapterInUse, match="live"):
            pool.evict("pinned")
        outs = door.run()
        assert len(outs) == 3
        pool.evict("pinned")


# ---------------------------------------------------------------------------
# distributed: TP sharding, DP evacuation, disaggregated handoff
# ---------------------------------------------------------------------------

class TestDistributed:
    def test_tp2_token_identical(self):
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        ws = _weights(model, seed=60)
        pool.load("a", ws)
        mesh = serving.serving_mesh(tp=2)
        eng = _engine(model=model, lora=pool, mesh=mesh).warmup()
        p = _prompt(9)
        rid = eng.add_request(p, max_new_tokens=6, adapter="a")
        outs = eng.run()
        assert outs[rid] == _merged_ref(ws, p, 6)
        assert eng.kv_blocks_used == 0

    def test_replica_set_requires_shared_pool(self):
        m1, m2 = _tiny(), _tiny()
        p1 = LoRAPool(m1, max_adapters=1, rank=8)
        p2 = LoRAPool(m2, max_adapters=1, rank=8)
        with pytest.raises(ValueError, match="share a single LoRAPool"):
            serving.EngineReplicaSet([
                _engine(model=m1, lora=p1), _engine(model=m2, lora=p2)])

    def test_dp_evacuation_preserves_adapter(self):
        """A replica failure mid-decode evacuates the adapter request
        through preempt→swap→restore onto the survivor — the adapter id
        must survive the migration like trace_id does, and outputs stay
        identical to the merged reference."""
        def build_set():
            m1, m2 = _tiny(), _tiny()
            pool = LoRAPool(m1, max_adapters=1, rank=8)
            ws = _weights(m1, seed=61)
            pool.load("a", ws)
            rset = serving.EngineReplicaSet(
                [_engine(model=m1, lora=pool),
                 _engine(model=m2, lora=pool)]).warmup()
            return rset, ws

        rset, ws = build_set()
        prompts = [_prompt(n) for n in (5, 17, 9, 26)]
        rs.clear_faults()
        rs.install_faults("serve.replica@4")
        try:
            rids = []
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for p in prompts:
                    rids.append(rset.add_request(p, max_new_tokens=6,
                                                 adapter="a"))
                    rset.step()
                outs = rset.run()
        finally:
            rs.clear_faults()
        assert rset.failures == 1
        for p, rid in zip(prompts, rids):
            assert outs[rid] == _merged_ref(ws, p, 6), \
                "evacuated adapter request diverged"
        for rep in rset.replicas:
            assert rep.kv_blocks_used == 0

    def test_handout_wire_carries_adapter(self):
        from paddle_tpu.serving.disagg import KVHandout
        model = _tiny()
        pool = LoRAPool(model, max_adapters=1, rank=8)
        pool.load("a", _weights(model, seed=62))
        eng = _engine(model=model, lora=pool, role="prefill").warmup()
        rid = eng.add_request(_prompt(9), max_new_tokens=6, adapter="a")
        while eng.has_work():
            eng.step()
        assert len(eng.handed_off) == 1
        st = eng.handed_off.popleft()
        h = KVHandout.from_bytes(KVHandout.from_state(st).to_bytes())
        assert h.adapter == "a"
        assert h.to_state().request.adapter == "a"
        assert pool.refcount("a") == 0     # released at handoff commit

    def test_disagg_handoff_token_identical(self):
        model_p, model_d = _tiny(), _tiny()
        pool = LoRAPool(model_p, max_adapters=1, rank=8)
        ws = _weights(model_p, seed=63)
        pool.load("a", ws)
        ds = serving.DisaggReplicaSet(
            [_engine(model=model_p, lora=pool, role="prefill")],
            [_engine(model=model_d, lora=pool, role="decode")]).warmup()
        p = _prompt(9)
        rid = ds.add_request(p, max_new_tokens=6, adapter="a")
        outs = ds.run()
        assert outs[rid] == _merged_ref(ws, p, 6)
        assert ds.disagg_stats()["handoffs"] == 1
        for rep in ds.replicas:
            assert rep.kv_blocks_used == 0
        assert pool.refcount("a") == 0

    def test_decode_tier_missing_adapter_typed(self):
        model = _tiny()
        from paddle_tpu.serving.disagg import KVHandout
        pool = LoRAPool(model, max_adapters=1, rank=8)
        pool.load("a", _weights(model, seed=64))
        pre = _engine(model=model, lora=pool, role="prefill").warmup()
        rid = pre.add_request(_prompt(9), max_new_tokens=6, adapter="a")
        while pre.has_work():
            pre.step()
        blob = KVHandout.from_state(pre.handed_off.popleft()).to_bytes()
        bare = _engine(role="decode").warmup()   # no pool loaded
        with pytest.raises(UnknownAdapter):
            bare.admit_handout(blob)


# ---------------------------------------------------------------------------
# telemetry + bench plumbing
# ---------------------------------------------------------------------------

class TestTelemetryAndBench:
    def test_metrics_and_report_fold(self, tmp_path):
        from paddle_tpu import observability as obs
        path = tmp_path / "tel.jsonl"
        tel = obs.enable(sinks=[obs.JsonlSink(str(path))],
                         crash_hooks=False)
        try:
            model = _tiny()
            pool = LoRAPool(model, max_adapters=2, rank=8)
            pool.load("a", _weights(model, seed=70))
            pool.load("b", _weights(model, seed=71))
            eng = _engine(model=model, lora=pool, max_batch=2).warmup()
            for ad in ("a", "b", None):
                eng.add_request(_prompt(5), max_new_tokens=4,
                                adapter=ad)
            eng.run()
            pool.evict("b")
            reg = obs.get_registry()
            snap = reg.snapshot()
            assert snap.get("serve.lora.active_adapters") == 1
            assert snap.get("serve.lora.loads") == 2
            assert snap.get("serve.lora.evictions") == 1
            assert snap.get("serve.lora.adapter[a].requests") == 1
            assert snap.get("serve.lora.adapter[a].tokens") == 4
            assert eng.lora_stats()["active_adapters"] == 1
        finally:
            obs.disable()
        import sys
        sys.path.insert(0, "tools")
        import telemetry_report as tr
        events, malformed = tr.load_events([str(path)])
        agg = tr.summarize(events)
        lora = tr._lora_stats(agg)
        assert lora["loads"] == 2 and lora["evictions"] == 1
        assert lora["adapters"]["a"]["tokens"] == 4
        assert lora["adapters"]["a"]["requests"] == 1
        text = tr.render(agg, malformed)
        assert "LoRA" in text

    @pytest.mark.slow
    def test_bench_serve_lora_plumbing(self):
        """CPU plumbing for the serve_lora_* bench rows: the batched
        multi-LoRA engine must beat the serial one-merged-engine-per-
        tenant deployment by >= 1.3x on the busy-time projection, with
        in-bench token identity (asserted inside the bench)."""
        import sys
        sys.path.insert(0, "tools")
        from decode_bench import bench_serve_lora
        r = bench_serve_lora(preset="tiny", n_adapters=3, rank=8,
                             max_batch=4, n_requests=8,
                             prompt_lens=(5, 9, 7, 12), max_new=8,
                             page_size=8)
        assert r["active_adapters"] == 3
        assert r["gen_tokens"] == 8 * 8
        assert r["vs_serial"] is not None and r["vs_serial"] >= 1.3, \
            f"batched multi-LoRA only {r['vs_serial']}x the serial " \
            "busy-time projection"
