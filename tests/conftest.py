"""Test harness: force an 8-device CPU mesh so every parallelism strategy is
exercised without TPU hardware (SURVEY.md §4: jax's virtual multi-device
host replaces the reference's multi-process NCCL test rigs)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell presets JAX_PLATFORMS=axon (TPU)
# deviceless-topology tests (test_memproof_dcn) load libtpu for COMPILE-ONLY
# use; without this the process holds the libtpu lockfile and the ci-gate
# subprocesses (test_ci_gates -> tools/memproof topologies) abort on it
os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) force-registers itself regardless of
# JAX_PLATFORMS; pin the config explicitly so tests run on the virtual
# 8-device CPU mesh.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite is compile-bound (~20 min cold), and
# every run recompiles identical tiny programs. Cache under .pytest_cache
# (gitignored) so warm runs skip XLA compilation entirely.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".pytest_cache",
                          "xla_cache")
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    pt.seed(1234)
    yield
