"""paddle.distribution parity tests: moments via sampling, log_prob vs
scipy-free closed forms, KL registry, jit-compatibility."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import distribution as D


KEY = jax.random.key(0)


class TestMomentsBySampling:
    @pytest.mark.parametrize("dist,mean,var", [
        (lambda: D.Normal(1.0, 2.0), 1.0, 4.0),
        (lambda: D.Uniform(0.0, 4.0), 2.0, 16 / 12),
        (lambda: D.Bernoulli(probs=0.3), 0.3, 0.21),
        (lambda: D.Beta(2.0, 3.0), 0.4, 0.04),
        (lambda: D.Gumbel(0.0, 1.0), 0.5772, np.pi ** 2 / 6),
        (lambda: D.Laplace(0.0, 1.5), 0.0, 4.5),
        (lambda: D.Exponential(2.0), 0.5, 0.25),
        (lambda: D.Geometric(0.4), 1.5, 3.75),
        (lambda: D.LogNormal(0.0, 0.5), np.exp(0.125), None),
    ])
    def test_sample_moments_match(self, dist, mean, var):
        d = dist()
        s = np.asarray(d.sample((20000,), key=KEY))
        assert np.isfinite(s).all()
        np.testing.assert_allclose(s.mean(), mean, atol=0.08)
        np.testing.assert_allclose(float(d.mean), mean, rtol=1e-4)
        if var is not None:
            np.testing.assert_allclose(s.var(), var, rtol=0.12)
            np.testing.assert_allclose(float(d.variance), var, rtol=1e-4)

    def test_categorical_and_dirichlet(self):
        c = D.Categorical(logits=jnp.log(jnp.array([0.2, 0.3, 0.5])))
        s = np.asarray(c.sample((20000,), key=KEY))
        freq = np.bincount(s, minlength=3) / s.size
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
        np.testing.assert_allclose(np.asarray(c.entropy()),
                                   -(np.array([.2, .3, .5])
                                     * np.log([.2, .3, .5])).sum(), rtol=1e-5)
        dir_ = D.Dirichlet(jnp.array([2.0, 3.0, 5.0]))
        sd = np.asarray(dir_.sample((5000,), key=KEY))
        np.testing.assert_allclose(sd.mean(0), [0.2, 0.3, 0.5], atol=0.02)
        np.testing.assert_allclose(sd.sum(-1), 1.0, atol=1e-5)


class TestLogProb:
    def test_normal_integrates(self):
        d = D.Normal(0.0, 1.0)
        x = jnp.linspace(-8, 8, 4001)
        total = jnp.trapezoid(d.prob(x), x)
        np.testing.assert_allclose(float(total), 1.0, atol=1e-4)
        np.testing.assert_allclose(float(d.log_prob(0.0)),
                                   -0.5 * np.log(2 * np.pi), rtol=1e-6)

    def test_bernoulli_logits_stable(self):
        d = D.Bernoulli(logits=40.0)
        assert np.isfinite(float(d.log_prob(1.0)))
        assert float(d.log_prob(1.0)) > -1e-6

    def test_categorical_log_prob_gather(self):
        c = D.Categorical(probs=jnp.array([[0.5, 0.5], [0.9, 0.1]]))
        lp = np.asarray(c.log_prob(jnp.array([0, 1])))
        np.testing.assert_allclose(lp, np.log([0.5, 0.1]), rtol=1e-5)


class TestKL:
    def test_normal_kl_closed_form_and_mc(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        kl = float(D.kl_divergence(p, q))
        s = p.sample((100000,), key=KEY)
        mc = float(jnp.mean(p.log_prob(s) - q.log_prob(s)))
        np.testing.assert_allclose(kl, mc, atol=0.02)

    def test_unregistered_raises(self):
        with pytest.raises(NotImplementedError, match="no KL"):
            D.kl_divergence(D.Normal(0, 1), D.Uniform(0, 1))

    def test_registry_extension(self):
        class My(D.Normal):
            pass

        @D.register_kl(My, My)
        def _kl(p, q):
            return jnp.zeros(())

        assert float(D.kl_divergence(My(0, 1), My(1, 2))) == 0.0


class TestJitAndRng:
    def test_inside_jit(self):
        @jax.jit
        def f(key, x):
            d = D.Normal(0.0, 1.0)
            return d.log_prob(x) + d.sample(key=key)

        assert np.isfinite(float(f(KEY, 0.3)))

    def test_global_rng_fallback(self):
        pt.seed(0)
        a = D.Normal(0.0, 1.0).sample((4,))
        pt.seed(0)
        b = D.Normal(0.0, 1.0).sample((4,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
