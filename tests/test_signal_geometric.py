"""paddle.signal (stft/istft round-trip) and paddle.geometric
(segment ops, send_u_recv/send_ue_recv) parity tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import geometric as G
from paddle_tpu import signal as S


class TestSignal:
    def test_stft_istft_roundtrip(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 2048)).astype(np.float32))
        spec = S.stft(x, n_fft=256, hop_length=64)
        assert spec.shape == (2, 129, 2048 // 64 + 1)
        back = S.istft(spec, n_fft=256, hop_length=64, length=2048)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   atol=1e-4)

    def test_istft_inside_jit(self):
        x = jnp.ones((1, 512))
        f = jax.jit(lambda x: S.istft(S.stft(x, n_fft=128, hop_length=32),
                                      n_fft=128, hop_length=32, length=512))
        np.testing.assert_allclose(np.asarray(f(x)), 1.0, atol=1e-4)


class TestGeometric:
    def test_segment_ops(self):
        data = jnp.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]])
        ids = jnp.array([0, 0, 1, 3])
        np.testing.assert_allclose(G.segment_sum(data, ids, out_size=4),
                                   [[4, 6], [5, 6], [0, 0], [7, 8]])
        np.testing.assert_allclose(G.segment_mean(data, ids, out_size=4),
                                   [[2, 3], [5, 6], [0, 0], [7, 8]])
        np.testing.assert_allclose(G.segment_max(data, ids, out_size=4),
                                   [[3, 4], [5, 6], [0, 0], [7, 8]])
        np.testing.assert_allclose(G.segment_min(data, ids, out_size=4),
                                   [[1, 2], [5, 6], [0, 0], [7, 8]])

    def test_send_u_recv(self):
        x = jnp.array([[1.0], [2.0], [4.0]])
        src = jnp.array([0, 1, 2, 2])
        dst = jnp.array([1, 2, 0, 0])
        out = G.send_u_recv(x, src, dst, reduce_op="sum", out_size=3)
        np.testing.assert_allclose(out, [[8.0], [1.0], [2.0]])
        out = G.send_u_recv(x, src, dst, reduce_op="mean", out_size=3)
        np.testing.assert_allclose(out, [[4.0], [1.0], [2.0]])

    def test_send_ue_recv_and_jit(self):
        x = jnp.array([[1.0], [2.0]])
        e = jnp.array([[10.0], [20.0]])
        src = jnp.array([0, 1])
        dst = jnp.array([1, 1])
        out = G.send_ue_recv(x, e, src, dst, "add", "sum", out_size=2)
        np.testing.assert_allclose(out, [[0.0], [33.0]])
        f = jax.jit(lambda x: G.send_u_recv(x, src, dst, "max", out_size=2))
        np.testing.assert_allclose(f(x), [[0.0], [2.0]])

    def test_bad_ops_raise(self):
        x = jnp.zeros((2, 1))
        with pytest.raises(ValueError, match="reduce_op"):
            G.send_u_recv(x, jnp.array([0]), jnp.array([1]), "prod", 2)
        with pytest.raises(ValueError, match="message_op"):
            G.send_ue_recv(x, x, jnp.array([0, 1]), jnp.array([0, 1]),
                           "pow", "sum", 2)


class TestSegmentEmptyAndIntDtypes:
    def test_segment_max_int_empty_segment(self):
        import paddle_tpu.geometric as G
        data = jnp.array([3, 1, 7], jnp.int32)
        ids = jnp.array([0, 0, 2])
        out = np.asarray(G.segment_max(data, ids, out_size=3))
        # empty segment 1 is zero, not INT_MIN
        np.testing.assert_array_equal(out, [3, 0, 7])
        out_min = np.asarray(G.segment_min(data, ids, out_size=3))
        np.testing.assert_array_equal(out_min, [1, 0, 7])

    def test_segment_max_keeps_legitimate_inf(self):
        import paddle_tpu.geometric as G
        data = jnp.array([float("inf"), 1.0, float("-inf")])
        ids = jnp.array([0, 0, 1])
        out = np.asarray(G.segment_max(data, ids, out_size=2))
        assert out[0] == np.inf          # real +inf max survives
        out_min = np.asarray(G.segment_min(data, ids, out_size=2))
        assert out_min[1] == -np.inf     # real -inf min survives
