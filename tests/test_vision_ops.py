"""paddle.vision.ops parity: nms, roi_align, box_iou — hand-computed
oracles (torchvision is not in the image)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.vision import ops as V


class TestBoxIou:
    def test_known_values(self):
        a = jnp.array([[0.0, 0, 2, 2], [0, 0, 1, 1]])
        b = jnp.array([[1.0, 1, 3, 3], [0, 0, 2, 2]])
        iou = np.asarray(V.box_iou(a, b))
        np.testing.assert_allclose(iou[0], [1 / 7, 1.0], atol=1e-6)
        np.testing.assert_allclose(iou[1], [0.0, 0.25], atol=1e-6)


class TestNms:
    def test_greedy_suppression(self):
        boxes = jnp.array([[0.0, 0, 10, 10],     # score .9 — kept
                           [1.0, 1, 10, 10],     # high IoU with 0 — dropped
                           [20.0, 20, 30, 30],   # kept
                           [0.0, 0, 5, 5]])      # IoU with 0 = .25 — kept @.3
        scores = jnp.array([0.9, 0.8, 0.7, 0.6])
        keep = np.asarray(V.nms(boxes, 0.3, scores))
        np.testing.assert_array_equal(keep, [0, 2, 3])

    def test_static_topk_jit(self):
        boxes = jnp.array([[0.0, 0, 10, 10], [1.0, 1, 10, 10],
                           [20.0, 20, 30, 30]])
        scores = jnp.array([0.9, 0.8, 0.7])
        f = jax.jit(lambda b, s: V.nms(b, 0.3, s, top_k=3))
        out = np.asarray(f(boxes, scores))
        np.testing.assert_array_equal(out, [0, 2, -1])

    def test_threshold_one_keeps_all(self):
        boxes = jnp.array([[0.0, 0, 2, 2], [0, 0, 2, 2]])
        keep = np.asarray(V.nms(boxes, 1.0, jnp.array([0.5, 0.9])))
        np.testing.assert_array_equal(keep, [1, 0])


class TestRoiAlign:
    def test_identity_roi_on_linear_image(self):
        # image = x coordinate; an aligned full-image roi sampled at the
        # pixel centres must reproduce the linear ramp exactly
        h = w = 8
        img = jnp.broadcast_to(jnp.arange(w, dtype=jnp.float32), (1, 1, h, w))
        boxes = jnp.array([[0.5, 0.5, w - 0.5, h - 0.5]])  # pixel-centre box
        out = np.asarray(V.roi_align(img, boxes, output_size=7,
                                     sampling_ratio=1))
        assert out.shape == (1, 1, 7, 7)
        expect = 0.5 + np.arange(7) + 0.0  # centres of 1-px bins from 0.5..7.5
        np.testing.assert_allclose(out[0, 0, 0], expect, atol=1e-5)
        # rows identical (image constant along y)
        np.testing.assert_allclose(out[0, 0], np.tile(expect, (7, 1)),
                                   atol=1e-5)

    def test_batch_routing_and_scale(self):
        x = jnp.stack([jnp.zeros((1, 4, 4)), jnp.ones((1, 4, 4))])
        boxes = jnp.array([[0.0, 0, 8, 8], [0.0, 0, 8, 8]])
        out = np.asarray(V.roi_align(x, boxes, boxes_num=jnp.array([1, 1]),
                                     output_size=2, spatial_scale=0.5))
        np.testing.assert_allclose(out[0], 0.0, atol=1e-6)
        np.testing.assert_allclose(out[1], 1.0, atol=1e-6)

    def test_grad_flows(self):
        x = jnp.ones((1, 2, 6, 6))
        boxes = jnp.array([[1.0, 1, 5, 5]])
        g = jax.grad(lambda x: V.roi_align(x, boxes, output_size=3).sum())(x)
        assert np.isfinite(np.asarray(g)).all() and float(jnp.abs(g).sum()) > 0


class TestRoiAlignAdaptiveSampling:
    def test_adaptive_matches_explicit_ratio(self):
        # roi of 8px mapped to a 2-bin output → adaptive sr = ceil(8/2) = 4;
        # must equal an explicit sampling_ratio=4 call exactly
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 2, 16, 16)).astype(np.float32))
        boxes = jnp.array([[2.0, 2.0, 10.0, 10.0]])
        adaptive = np.asarray(V.roi_align(x, boxes, output_size=2,
                                          sampling_ratio=-1))
        explicit = np.asarray(V.roi_align(x, boxes, output_size=2,
                                          sampling_ratio=4))
        np.testing.assert_allclose(adaptive, explicit, rtol=1e-5, atol=1e-6)

    def test_adaptive_per_roi(self):
        # two rois of different sizes get different per-roi sample counts;
        # each must match its own explicit-ratio call
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(1, 1, 16, 16)).astype(np.float32))
        small = jnp.array([[1.0, 1.0, 3.0, 3.0]])    # 2px/2bins → sr 1
        large = jnp.array([[0.0, 0.0, 12.0, 12.0]])  # 12px/2bins → sr 6
        both = np.asarray(V.roi_align(
            x, jnp.concatenate([small, large]), output_size=2,
            sampling_ratio=-1))
        np.testing.assert_allclose(
            both[0], np.asarray(V.roi_align(x, small, output_size=2,
                                            sampling_ratio=1))[0],
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            both[1], np.asarray(V.roi_align(x, large, output_size=2,
                                            sampling_ratio=6))[0],
            rtol=1e-5, atol=1e-6)
