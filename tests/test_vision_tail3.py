"""Round-3 vision ops tail — oracle tests (torch for roi/deform; analytic
for the detection box ops)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import paddle_tpu.vision.ops as VO


class TestRoiPooling:
    def test_roi_pool_analytic(self):
        # 1x1x4x4 ramp, one roi covering the full map, 2x2 output
        x = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
        out = VO.roi_pool(x, jnp.asarray([[0., 0., 3., 3.]]), None, 2)
        np.testing.assert_allclose(np.asarray(out)[0, 0],
                                   [[5., 7.], [13., 15.]])

    def test_psroi_pool_analytic(self):
        # C = out_c * oh * ow = 1*2*2: each bin reads its own channel
        x = jnp.stack([jnp.full((4, 4), float(i)) for i in range(4)])[None]
        out = VO.psroi_pool(x, jnp.asarray([[0., 0., 4., 4.]]), None, 2)
        np.testing.assert_allclose(np.asarray(out)[0, 0],
                                   [[0., 1.], [2., 3.]])

    def test_deform_conv_zero_offset_is_conv(self, rng):
        import torch.nn.functional as tF
        x = rng.standard_normal((2, 4, 10, 10)).astype("float32")
        w = rng.standard_normal((6, 4, 3, 3)).astype("float32")
        off = jnp.zeros((2, 2 * 9, 10, 10))
        ours = VO.deform_conv2d(jnp.asarray(x), off, jnp.asarray(w),
                                padding=1)
        ref = tF.conv2d(torch.tensor(x), torch.tensor(w), padding=1)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_deform_conv_integer_offset_shifts(self, rng):
        # offset (0, +1) on every tap == conv over x shifted left by 1
        import torch.nn.functional as tF
        x = rng.standard_normal((1, 2, 8, 8)).astype("float32")
        w = rng.standard_normal((3, 2, 3, 3)).astype("float32")
        off = np.zeros((1, 2 * 9, 8, 8), np.float32)
        off[:, 1::2] = 1.0   # x-offsets (reference layout: y, x per tap)
        ours = np.asarray(VO.deform_conv2d(jnp.asarray(x),
                                           jnp.asarray(off),
                                           jnp.asarray(w), padding=1))
        xs = np.zeros_like(x)
        xs[..., :-1] = x[..., 1:]
        ref = tF.conv2d(torch.tensor(xs), torch.tensor(w),
                        padding=1).numpy()
        # interior only (border taps sample the zero pad differently)
        np.testing.assert_allclose(ours[..., 1:-1, 1:-2],
                                   ref[..., 1:-1, 1:-2], rtol=1e-3,
                                   atol=1e-4)


class TestBoxOps:
    def test_box_coder_encode_decode_roundtrip(self, rng):
        priors = jnp.asarray([[0., 0., 10., 10.], [5., 5., 20., 25.]])
        var = [0.1, 0.1, 0.2, 0.2]
        targets = jnp.asarray([[1., 2., 8., 9.], [6., 4., 18., 28.]])
        enc = VO.box_coder(priors, var, targets, "encode_center_size")
        # decode the diagonal (prior i with its own code) back
        deltas = jnp.stack([enc[0, 0], enc[1, 1]])
        dec = VO.box_coder(priors, var, deltas, "decode_center_size")
        rec = jnp.stack([dec[0, 0], dec[1, 0]])
        np.testing.assert_allclose(np.asarray(rec), np.asarray(targets),
                                   atol=1e-4)

    def test_box_coder_per_prior_variance_decode(self):
        priors = jnp.asarray([[0., 0., 10., 10.]])
        pvar = jnp.asarray([[0.1, 0.2, 0.3, 0.4]])
        deltas = jnp.asarray([[1.0, 1.0, 0.5, 0.5]])
        dec = np.asarray(VO.box_coder(priors, pvar, deltas,
                                      "decode_center_size"))[0, 0]
        # cx = 0.1*1*10 + 5; cy = 0.2*1*10 + 5; w = exp(0.3*0.5)*10 ...
        w = np.exp(0.15) * 10
        h = np.exp(0.2) * 10
        np.testing.assert_allclose(
            dec, [6 - w / 2, 7 - h / 2, 6 + w / 2, 7 + h / 2], rtol=1e-5)

    def test_matrix_nms_decay_ordering(self):
        # three same-class boxes: A (score .9), B overlaps A heavily
        # (score .8), C overlaps B but not A (score .7).  B must decay
        # hard; C's decay is compensated by B's own suppression.
        boxes = jnp.asarray([[0., 0., 10., 10.],
                             [0., 0., 10., 9.],      # iou(A,B) ~ .9
                             [0., 8., 10., 18.]])    # overlaps B a bit
        scores = jnp.asarray([[0.9, 0.8, 0.7]])
        out, idx = VO.matrix_nms(boxes, scores, score_threshold=0.0,
                                 nms_top_k=3, keep_top_k=3)
        out = np.asarray(out)
        by_idx = {int(i): float(s) for i, s in zip(np.asarray(idx),
                                                   out[:, 1])}
        assert by_idx[0] == pytest.approx(0.9)        # top box undecayed
        assert by_idx[1] < 0.15                       # heavy overlap decays
        # C only mildly overlaps B (iou ~ .05 with B, 0.09 with A):
        # stays close to its raw score
        assert by_idx[2] > 0.5

    def test_yolo_box_shapes_and_zeroing(self, rng):
        x = jnp.asarray(rng.standard_normal((2, 3 * 85, 5, 5))
                        .astype("float32"))
        boxes, scores = VO.yolo_box(x, jnp.asarray([[320, 320], [416, 416]]),
                                    [10, 13, 16, 30, 33, 23], 80,
                                    conf_thresh=0.5)
        assert boxes.shape == (2, 75, 4) and scores.shape == (2, 75, 80)
        b = np.asarray(boxes)
        s = np.asarray(scores)
        dead = s.sum(-1) == 0
        assert (np.abs(b[dead]).sum() == 0)  # suppressed rows are zero

    def test_prior_box_counts(self):
        pb, var = VO.prior_box(jnp.zeros((1, 1, 4, 4)),
                               jnp.zeros((1, 3, 32, 32)),
                               min_sizes=[8.0], max_sizes=[16.0],
                               aspect_ratios=[2.0], flip=True, clip=True)
        # 1 min + 1 sqrt(min*max) + 2 ar boxes = 4 per cell
        assert pb.shape == (4, 4, 4, 4) and var.shape == pb.shape
        assert float(pb.min()) >= 0.0 and float(pb.max()) <= 1.0

    def test_distribute_fpn_proposals(self):
        rois = jnp.asarray([[0., 0., 32., 32.], [0., 0., 224., 224.],
                            [0., 0., 64., 64.]])
        outs, masks, restore = VO.distribute_fpn_proposals(
            rois, 2, 5, 4, 224)
        lvls = np.asarray([np.asarray(m) for m in masks])
        assert lvls.sum() == 3                       # each roi routed once
        assert np.asarray(masks[4 - 2])[1]           # refer-scale -> level 4
        assert np.asarray(masks[0])[0]               # small roi -> level 2
        assert len(np.asarray(restore)) == 3


class TestYoloLoss:
    """yolo_loss self-consistency (the reference mount is empty, so the
    oracle is the YOLOv3 recipe itself: perfect predictions cost ~0,
    padding rows cost 0, gradients flow, ignore_thresh drops overlapping
    negatives)."""

    def _setup(self, rng, n=2, h=4, w=4, na=3, classes=5):
        c = na * (5 + classes)
        x = jnp.asarray(rng.standard_normal((n, c, h, w))
                        .astype("float32")) * 0.1
        gt_box = jnp.asarray([[[0.4, 0.4, 0.3, 0.4], [0, 0, 0, 0]],
                              [[0.7, 0.2, 0.2, 0.2], [0.2, 0.8, 0.4, 0.3]]],
                             jnp.float32)
        gt_label = jnp.asarray([[1, 0], [3, 2]])
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                   116, 90, 156, 198, 373, 326]
        return x, gt_box, gt_label, anchors

    def test_finite_and_positive(self, rng):
        x, gt_box, gt_label, anchors = self._setup(rng)
        loss = VO.yolo_loss(x, gt_box, gt_label, anchors, [0, 1, 2], 5,
                            ignore_thresh=0.5, downsample_ratio=32)
        assert loss.shape == (2,)
        assert bool(jnp.isfinite(loss).all()) and float(loss.min()) > 0

    def test_padding_rows_do_not_contribute(self, rng):
        x, gt_box, gt_label, anchors = self._setup(rng)
        args = (anchors, [0, 1, 2], 5)
        base = VO.yolo_loss(x, gt_box, gt_label, *args,
                            ignore_thresh=0.5, downsample_ratio=32)
        # change the LABEL of a padding (zero-area) row: loss unchanged
        gt_label2 = gt_label.at[0, 1].set(4)
        same = VO.yolo_loss(x, gt_box, gt_label2, *args,
                            ignore_thresh=0.5, downsample_ratio=32)
        np.testing.assert_allclose(np.asarray(base), np.asarray(same))

    def test_gradient_flows_and_training_reduces_loss(self, rng):
        x, gt_box, gt_label, anchors = self._setup(rng)

        def f(x):
            return VO.yolo_loss(x, gt_box, gt_label, anchors, [0, 1, 2],
                                5, ignore_thresh=0.5,
                                downsample_ratio=32).sum()

        g = jax.grad(f)(x)
        assert bool(jnp.isfinite(g).all()) and float(jnp.abs(g).max()) > 0
        x2 = x
        for _ in range(60):
            x2 = x2 - 0.5 * jax.grad(f)(x2)
        assert float(f(x2)) < float(f(x)) * 0.5

    def test_ignore_thresh_drops_overlapping_negatives(self, rng):
        x, gt_box, gt_label, anchors = self._setup(rng)
        args = (anchors, [0, 1, 2], 5)
        strict = VO.yolo_loss(x, gt_box, gt_label, *args,
                              ignore_thresh=0.99, downsample_ratio=32)
        lax_ = VO.yolo_loss(x, gt_box, gt_label, *args,
                            ignore_thresh=0.01, downsample_ratio=32)
        # a lower threshold ignores MORE negatives -> loss can only drop
        assert float(lax_.sum()) <= float(strict.sum()) + 1e-5
