"""C++ runtime component tests: the native TCPStore server must speak the
exact Python-client protocol; BlockingQueue semantics; collate fast path."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu import runtime_native as rn
from paddle_tpu.launch.store import TCPStore, free_port

pytestmark = pytest.mark.skipif(not rn.available(),
                                reason="native lib not built (no toolchain)")


class TestNativeStore:
    def test_python_client_against_cpp_server(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True, native=True)
        assert s._native_server is not None  # really the C++ server
        c = TCPStore(s.endpoint)
        try:
            s.set("k", b"v1")
            assert c.get("k") == b"v1"
            assert c.add("n", 5) == 5
            assert s.add("n", -2) == 3
            assert c.keys("") == ["k", "n"]
            assert s.compare_set("c", b"", b"x")
            assert not c.compare_set("c", b"y", b"z")
            assert c.delete("k") and not c.delete("k")

            def setter():
                time.sleep(0.2)
                c.set("late", b"yes")
            t = threading.Thread(target=setter)
            t.start()
            assert s.wait("late", timeout=5) == b"yes"
            t.join()
            with pytest.raises(TimeoutError):
                c.wait("never", timeout=0.2)
        finally:
            c.close()
            s.close()

    def test_cpp_server_barrier(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True, native=True)
        c = TCPStore(s.endpoint)
        errs = []
        def one(store):
            try:
                store.barrier("b", 2, timeout=5)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        try:
            ts = [threading.Thread(target=one, args=(x,)) for x in (s, c)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs
        finally:
            c.close()
            s.close()

    def test_malformed_request_keeps_server_alive(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True, native=True)
        c = TCPStore(s.endpoint)
        try:
            c.set("n", b"not-a-number")
            # add on a non-numeric value must fail THIS request only
            with pytest.raises(Exception):
                c.add("n", 1)
            c2 = TCPStore(s.endpoint)
            c2.set("ok", b"1")        # server still alive and serving
            assert s.get("ok") == b"1"
            c2.close()
        finally:
            c.close()
            s.close()

    def test_close_with_connected_client_does_not_hang(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True, native=True)
        c = TCPStore(s.endpoint)   # stays connected
        t0 = time.time()
        s.close()                  # must not block on the live client
        assert time.time() - t0 < 5
        c.close()

    def test_hostname_binding(self):
        s = TCPStore(f"localhost:{free_port()}", is_master=True, native=True)
        try:
            c = TCPStore(s.endpoint)
            c.set("h", b"1")
            assert s.get("h") == b"1"
            c.close()
        finally:
            s.close()

    def test_ephemeral_port_assignment(self):
        s = TCPStore("127.0.0.1:0", is_master=True, native=True)
        try:
            assert not s.endpoint.endswith(":0")
            c = TCPStore(s.endpoint)
            c.set("x", b"1")
            assert s.get("x") == b"1"
            c.close()
        finally:
            s.close()


class TestNativeQueue:
    def test_fifo_and_blocking(self):
        q = rn.BlockingQueue(4)
        try:
            for i in range(4):
                assert q.push(f"item{i}".encode())
            assert len(q) == 4
            # full queue: push times out
            assert not q.push(b"overflow", timeout=0.1)
            got = [q.pop() for _ in range(4)]
            assert got == [b"item0", b"item1", b"item2", b"item3"]
            with pytest.raises(TimeoutError):
                q.pop(timeout=0.1)
        finally:
            q.close()
            q.destroy()

    def test_producer_consumer_threads(self):
        q = rn.BlockingQueue(2)
        received = []
        def consumer():
            while True:
                b = q.pop(timeout=10)
                if b is None:
                    return
                received.append(b)
        t = threading.Thread(target=consumer)
        t.start()
        for i in range(20):
            q.push(str(i).encode() * 100)
        time.sleep(0.2)
        q.close()
        t.join(timeout=10)
        q.destroy()
        assert len(received) == 20
        assert received[7] == b"7" * 100

    def test_close_unblocks_pop(self):
        q = rn.BlockingQueue(2)
        result = {}
        def popper():
            result["v"] = q.pop(timeout=30)
        t = threading.Thread(target=popper)
        t.start()
        time.sleep(0.1)
        q.close()
        t.join(timeout=5)
        assert not t.is_alive() and result["v"] is None
        q.destroy()


class TestNativeCollate:
    def test_matches_np_stack(self):
        arrs = [np.random.default_rng(i).normal(size=(16, 32)).astype("float32")
                for i in range(8)]
        out = rn.collate_stack(arrs)
        np.testing.assert_array_equal(out, np.stack(arrs))
        assert out.dtype == np.float32

    def test_fast_path_declines_mixed(self):
        assert rn.collate_stack([np.zeros((2, 2)), np.zeros((3, 2))]) is None
        assert rn.collate_stack(
            [np.zeros((2, 2), "float32"), np.zeros((2, 2), "int32")]) is None
        # object dtype would memcpy borrowed PyObject* — must decline
        objs = [np.array(["a", "bb"], dtype=object) for _ in range(2)]
        assert rn.collate_stack(objs) is None

    def test_dataloader_uses_it(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        x = np.arange(64, dtype="float32").reshape(16, 4)
        y = np.arange(16, dtype="int64")
        dl = DataLoader(TensorDataset([x, y]), batch_size=4)
        batches = list(dl)
        assert len(batches) == 4
        np.testing.assert_array_equal(batches[0][0], x[:4])
        np.testing.assert_array_equal(batches[0][1], y[:4])
