"""BASELINE.json configs, exercised end-to-end at tiny scale.

Each of the五 target configs (BASELINE.json "configs") gets one test that
instantiates the SAME model family + parallelism strategy on the virtual
8-device mesh and runs real train steps to a falling loss:

1. Llama pure-DP (+ZeRO-1 on the dp axis)
2. ERNIE/GPT 13B-family TP+PP hybrid
3. Mixtral-style expert parallel (all-to-all over ep)
4. SDXL UNet conv/GroupNorm/attention
5. Llama 70B-family ZeRO-3 sharding

The full-scale presets themselves (llama2-7b/70b, gpt3-13b, sdxl) are
asserted to exist with the right dimensions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW


def _lm_batch(vocab, b=8, s=16, seed=0):
    r = np.random.default_rng(seed)
    ids = r.integers(0, vocab, (b, s + 1)).astype("int32")
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:].astype("int64"))}


def _train(model, loss_fn, batch, steps=12, lr=5e-3):
    opt = AdamW(learning_rate=lr, parameters=model.parameters())
    step = TrainStep(model, loss_fn, opt)
    state = step.init_state()
    losses = []
    for _ in range(steps):
        state, met = step(state, batch)
        losses.append(float(met["loss"]))
    return losses


@pytest.fixture
def hybrid(request):
    def make(**degrees):
        s = fleet.DistributedStrategy()
        s.hybrid_configs = degrees
        return fleet.init(is_collective=True, strategy=s)
    yield make
    fleet._reset()


class TestBaselineConfigs:
    def test_cfg1_llama_pure_dp_zero1(self, hybrid):
        from paddle_tpu.models.llama import PRESETS, causal_lm_loss, llama
        # full-scale preset sanity (llama2-7b is the real target)
        assert PRESETS["llama2-7b"].hidden_size == 4096
        hybrid(dp_degree=4, sharding_degree=2)   # DP + ZeRO-1-style opt shard
        m = llama("tiny")
        losses = _train(m, causal_lm_loss, _lm_batch(256))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_cfg2_ernie_tp_pp(self, hybrid):
        from paddle_tpu.models.gpt import GPTConfig, PRESETS, gpt
        assert PRESETS["gpt3-13b"].hidden_size == 5120    # 13B-class target
        hybrid(mp_degree=2, pp_degree=2, dp_degree=2)
        m = gpt(GPTConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=4, num_attention_heads=2,
                          max_position_embeddings=32, pipeline_stages=2,
                          num_microbatches=2))
        losses = _train(
            m, lambda mm, b: mm(b["input_ids"], labels=b["labels"]),
            _lm_batch(128, b=4, s=16))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_cfg3_moe_expert_parallel(self, hybrid):
        from paddle_tpu.models.mixtral import causal_lm_loss, mixtral
        hybrid(ep_degree=4, dp_degree=2)
        m = mixtral("tiny")
        losses = _train(m, causal_lm_loss, _lm_batch(256, b=8, s=8))
        assert losses[-1] < losses[0], losses

    def test_cfg4_sdxl_unet(self):
        from paddle_tpu.models.sdxl_unet import sdxl_unet
        pt.seed(0)
        m = sdxl_unet("tiny")
        r = np.random.default_rng(0)
        batch = {"x": jnp.asarray(r.normal(size=(2, 4, 16, 16)).astype("float32")),
                 "t": jnp.array([7, 420]),
                 "ctx": jnp.asarray(r.normal(size=(2, 6, 64)).astype("float32")),
                 "added": jnp.asarray(r.normal(size=(2, 96)).astype("float32")),
                 "eps": jnp.asarray(r.normal(size=(2, 4, 16, 16)).astype("float32"))}

        def diff_loss(mm, b):
            return ((mm(b["x"], b["t"], b["ctx"], b["added"]) - b["eps"]) ** 2).mean()

        losses = _train(m, diff_loss, batch, lr=2e-4)
        assert losses[-1] < losses[0], losses

    def test_cfg5_llama70b_family_zero3(self, hybrid):
        from paddle_tpu.models.llama import PRESETS, causal_lm_loss, llama
        p70 = PRESETS["llama2-70b"]
        assert (p70.hidden_size, p70.num_hidden_layers,
                p70.num_key_value_heads) == (8192, 80, 8)  # GQA 70B target
        hybrid(sharding_degree=8)
        m = llama("tiny")
        opt = AdamW(learning_rate=5e-3, parameters=m.parameters())
        step = TrainStep(m, causal_lm_loss, opt, zero_stage=3)
        state = step.init_state()
        batch = _lm_batch(256)
        losses = []
        for _ in range(12):
            state, met = step(state, batch)
            losses.append(float(met["loss"]))
        assert losses[-1] < losses[0] * 0.7, losses
        # params really are sharded over the sharding axis
        specs = step.param_specs()
        assert any("sharding" in str(s) for s in specs.values())
