"""TP-sharded (mp) KV-cache decode — multichip serving (VERDICT r4 #3).

Reference capability: fused_multi_transformer serving under model
parallelism (SURVEY §2.1 masked_multihead_attention serving mode): vocab/
head-parallel projections, KV caches sharded over the mp axis, greedy
tokens identical to the single-device rollout.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.models.llama import llama


@pytest.fixture(autouse=True)
def reset_fleet():
    yield
    fleet._reset()


def _serial_reference(ids, new, eos=None):
    pt.seed(0)
    m = llama("tiny", max_position_embeddings=64).eval()
    sd = {k: np.asarray(v) for k, v in m.state_dict().items()}
    out = np.asarray(m.generate(ids, max_new_tokens=new, eos_token_id=eos))
    return sd, out


@pytest.mark.parametrize("eos", [None, 7])
def test_mp_sharded_greedy_decode_matches_serial(eos):
    ids = jax.random.randint(jax.random.key(1), (4, 12), 0, 256)
    sd, ref = _serial_reference(ids, 10, eos)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"mp_degree": 2, "dp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    m = llama("tiny", max_position_embeddings=64).eval()
    m.set_state_dict(sd)
    with hcg.mesh:
        got = np.asarray(m.generate(ids, max_new_tokens=10,
                                    eos_token_id=eos))
    np.testing.assert_array_equal(got, ref)


def test_mp_sharded_decode_cache_layout_sharded():
    """The KV caches inside the sharded decode really are head-sharded
    over mp (not replicated): check the prefilled cache's sharding."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"mp_degree": 2, "dp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    pt.seed(0)
    m = llama("tiny", max_position_embeddings=64).eval()
    ids = jax.random.randint(jax.random.key(1), (4, 12), 0, 256)
    from paddle_tpu.nn.layer import serving_params
    with hcg.mesh:
        params = serving_params(m)
        prefill = m._prefill_fn()
        caches = m.model.init_cache(4, 32)
        _, caches = prefill(params, ids, caches)
        k0 = jax.tree.leaves(caches)[0]
        # (b, s, h_kv, d): the HEAD axis (dim 2) must be split over mp —
        # batch-only sharding would pass a mere not-replicated check
        spec = tuple(k0.sharding.spec)
        assert len(spec) >= 3 and spec[2] == "mp", \
            f"kv cache head axis not mp-sharded: {k0.sharding}"
