"""Flash-attention kernel numerics on CPU via the Pallas interpreter
(authoritative TPU runs happen in verify/bench; these keep CI coverage)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.pallas.flash_attention as fa


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    # run pallas_call in interpreter mode on CPU
    import jax.experimental.pallas as pl
    real_call = pl.pallas_call
    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(real_call, interpret=True))
    yield


def _oracle(q, k, v, causal):
    q64, k64, v64 = [np.asarray(t, np.float64) for t in (q, k, v)]
    b, s, h, d = q64.shape
    hkv = k64.shape[2]
    if hkv != h:
        k64 = np.repeat(k64, h // hkv, axis=2)
        v64 = np.repeat(v64, h // hkv, axis=2)
    logits = np.einsum("bqhd,bkhd->bhqk", q64, k64) / np.sqrt(d)
    if causal:
        m = np.tril(np.ones((s, s), bool))
        logits = np.where(m, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v64)


@pytest.mark.parametrize("b,s,h,hkv,d,causal", [
    (1, 128, 2, 2, 32, True),
    (2, 64, 4, 2, 16, True),
    (1, 128, 2, 2, 32, False),
])
def test_flash_fwd_matches_oracle(rng, b, s, h, hkv, d, causal):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    out = fa.flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _oracle(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_flash_bwd_matches_xla_grads(rng):
    b, s, h, d = 1, 128, 2, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    from paddle_tpu.nn.functional import _xla_attention

    def loss_fa(q, k, v):
        return (fa.flash_attention(q, k, v, causal=True, block_q=64,
                                   block_k=64) * w).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, is_causal=True) * w).sum()

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-4, err_msg=f"d{name}")


def test_flash_gqa_bwd(rng):
    b, s, h, hkv, d = 1, 64, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))

    from paddle_tpu.nn.functional import _xla_attention

    g_fa = jax.grad(lambda *a: fa.flash_attention(*a, causal=True, block_q=32,
                                                  block_k=32).sum(),
                    argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: _xla_attention(*a, is_causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-4, err_msg=f"d{name}")


def test_block_picker():
    assert fa._pick_block(2048, 512) == 512
    assert fa._pick_block(100, 512) == 100  # fits whole
    assert fa._pick_block(100, 64) == 4     # halves until it divides
    assert fa._pick_block(8, 512) == 8


def test_causal_bottom_right_alignment(rng):
    """sq != sk: causal mask must align bottom-right like the XLA fallback
    (decode-with-cache shape)."""
    from paddle_tpu.nn.functional import _xla_attention
    b, sq, sk, h, d = 1, 32, 64, 2, 16
    q = jnp.asarray(rng.standard_normal((b, sq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))
    out = fa.flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = _xla_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)
    # grads too
    g = jax.grad(lambda *a: fa.flash_attention(*a, causal=True, block_q=16,
                                               block_k=16).sum(),
                 argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: _xla_attention(*a, is_causal=True).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-4, err_msg=f"d{name}")


def test_supported_rejects_non_4d():
    assert not fa.supported(jnp.zeros((4, 8, 16)), jnp.zeros((4, 8, 16)),
                            jnp.zeros((4, 8, 16)))


def test_supported_rejects_causal_sq_gt_sk():
    """Causal with more queries than keys has fully-masked rows; the kernel
    must defer to the XLA fallback rather than emit uniform attention."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    q = jnp.zeros((1, 64, 4, 32))
    k = v = jnp.zeros((1, 32, 4, 32))
    assert not fa.supported(q, k, v, causal=True)
    assert fa.supported(q, k, v, causal=False)
    assert fa.supported(k, q, q, causal=True)  # sq < sk is fine
