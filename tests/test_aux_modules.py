"""Tests for config / device / metrics / profiler (reference patterns:
test/legacy_test/test_metrics.py numpy-oracle checks, profiler state
machine tests)."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt


# -- config -----------------------------------------------------------------

def test_train_config_roundtrip():
    from paddle_tpu.config import TrainConfig
    c = TrainConfig(amp_level="O2", max_steps=100).replace(seed=7)
    back = TrainConfig.from_json(c.to_json())
    assert back.amp_level == "O2" and back.max_steps == 100 and back.seed == 7


def test_distributed_strategy_exported_from_config():
    from paddle_tpu.config import DistributedStrategy
    s = DistributedStrategy(hybrid_configs={"dp_degree": 2, "mp_degree": 4})
    assert DistributedStrategy.from_json(s.to_json()).hybrid_configs["mp_degree"] == 4


# -- device -----------------------------------------------------------------

def test_device_api():
    from paddle_tpu import device
    assert device.device_count() == 8  # conftest forces 8 virtual devices
    assert "cpu" in device.get_device()
    s = device.current_stream()
    e1 = s.record_event()
    import time
    time.sleep(0.05)
    e2 = s.record_event()
    ms = e1.elapsed_time(e2)
    assert 40.0 < ms < 5000.0  # measures the gap between the record() calls
    s.synchronize()
    assert e2.query()


# -- metrics ----------------------------------------------------------------

def test_accuracy_topk():
    from paddle_tpu.metrics import Accuracy
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]])
    label = np.array([[1], [2]])  # first correct, second wrong
    m.update(m.compute(pred, label))
    top1, top2 = m.accumulate()
    assert top1 == pytest.approx(0.5)
    assert top2 == pytest.approx(0.5)  # class 2 is not in top-2 of row 2? row2 top2={0,1}
    assert m.name() == ["acc_top1", "acc_top2"]


def test_accuracy_single_k_scalar():
    from paddle_tpu.metrics import Accuracy
    m = Accuracy()
    pred = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = np.array([0, 1, 1])
    m.update(m.compute(pred, label))
    assert m.accumulate() == pytest.approx(2 / 3)


def test_precision_recall():
    from paddle_tpu.metrics import Precision, Recall
    preds = np.array([0.9, 0.8, 0.2, 0.7])
    labels = np.array([1, 0, 1, 1])
    p = Precision()
    p.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)  # TP=2 (0.9,0.7), FP=1 (0.8)
    r = Recall()
    r.update(preds, labels)
    assert r.accumulate() == pytest.approx(2 / 3)  # FN=1 (0.2)


def test_auc_perfect_and_random():
    from paddle_tpu.metrics import Auc
    m = Auc()
    m.update(np.array([0.9, 0.8, 0.1, 0.2]), np.array([1, 1, 0, 0]))
    assert m.accumulate() == pytest.approx(1.0, abs=1e-3)
    m2 = Auc()
    m2.update(np.array([0.5, 0.5, 0.5, 0.5]), np.array([1, 0, 1, 0]))
    assert m2.accumulate() == pytest.approx(0.5, abs=1e-2)
    # oracle vs sklearn-style exact computation on mixed scores
    scores = np.array([0.1, 0.4, 0.35, 0.8])
    labels = np.array([0, 0, 1, 1])
    m3 = Auc()
    m3.update(scores, labels)
    assert m3.accumulate() == pytest.approx(0.75, abs=1e-2)


# -- profiler ---------------------------------------------------------------

def test_make_scheduler_states():
    from paddle_tpu.profiler import ProfilerState, make_scheduler
    sch = make_scheduler(closed=1, ready=1, record=2, repeat=1, skip_first=1)
    states = [sch(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED          # closed
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # repeat exhausted


def test_profiler_records_and_exports(tmp_path):
    from paddle_tpu import profiler as prof_mod
    got = {}

    def on_ready(p):
        got["rows"] = p.aggregate()
        got["path"] = p.export(str(tmp_path / "trace.json"))

    p = prof_mod.Profiler(timer_only=True, on_trace_ready=on_ready)
    p.start()
    for _ in range(3):
        with prof_mod.RecordEvent("forward"):
            np.dot(np.ones((64, 64)), np.ones((64, 64)))
        with prof_mod.RecordEvent("backward"):
            pass
        p.step()
    p.stop()
    names = {r[0] for r in got["rows"]}
    assert "forward" in names and "backward" in names
    trace = json.load(open(got["path"]))
    assert any(e["name"] == "forward" for e in trace["traceEvents"])
    fwd = next(r for r in got["rows"] if r[0] == "forward")
    assert fwd[1] == 3 and fwd[2] > 0


def test_profiler_scheduler_gates_recording():
    from paddle_tpu import profiler as prof_mod
    p = prof_mod.Profiler(timer_only=True,
                          scheduler=prof_mod.make_scheduler(closed=2, ready=0,
                                                            record=2))
    p.start()
    for i in range(4):
        with prof_mod.RecordEvent("op"):
            pass
        p.step()
    # steps 0,1 closed; 2,3 recording -> exactly 2 'op' events kept
    assert sum(1 for e in p._events if e.name == "op") == 2
    p.stop()


def test_make_scheduler_skip_first_and_repeat_edges():
    from paddle_tpu.profiler import ProfilerState, make_scheduler
    # repeat=0: cycles forever — the record window recurs every cycle
    sch = make_scheduler(closed=1, ready=0, record=1)
    assert [sch(i) for i in range(6)] == [
        ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN] * 3
    # closed=0, ready=0: every step is a one-step record window
    sch = make_scheduler(closed=0, ready=0, record=1)
    assert sch(0) == sch(7) == ProfilerState.RECORD_AND_RETURN
    # skip_first offsets the whole cycle train; repeat counts cycles
    # AFTER the skip (reference semantics)
    sch = make_scheduler(closed=0, ready=1, record=1, repeat=2,
                         skip_first=3)
    assert [sch(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    assert sch(3) == ProfilerState.READY
    assert sch(4) == ProfilerState.RECORD_AND_RETURN
    assert sch(5) == ProfilerState.READY
    assert sch(6) == ProfilerState.RECORD_AND_RETURN
    assert sch(7) == ProfilerState.CLOSED          # repeat exhausted
    # a multi-step record window: last step is RECORD_AND_RETURN
    sch = make_scheduler(closed=0, ready=0, record=3, repeat=1)
    assert [sch(i) for i in range(4)] == [
        ProfilerState.RECORD, ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN, ProfilerState.CLOSED]


def test_profiler_window_exports_exactly_once():
    """Regression: a RECORD_AND_RETURN boundary whose next scheduled
    state is still recording (closed=0 back-to-back cycles) fired
    on_trace_ready in step() AND again in stop() for the same window."""
    from paddle_tpu import profiler as prof_mod
    exports = []
    p = prof_mod.Profiler(
        timer_only=True,
        scheduler=prof_mod.make_scheduler(closed=0, ready=0, record=1),
        on_trace_ready=lambda prof: exports.append(prof._step))
    p.start()
    with prof_mod.RecordEvent("op"):
        pass
    p.step()          # window 0 exports here...
    p.stop()          # ...and must NOT re-export it
    assert exports == [0]


def test_profiler_stop_still_exports_partial_window():
    """stop() mid-window (no RECORD_AND_RETURN seen) keeps exporting —
    the dedupe only suppresses the double fire."""
    from paddle_tpu import profiler as prof_mod
    exports = []
    p = prof_mod.Profiler(timer_only=True,
                          on_trace_ready=lambda prof: exports.append(1))
    p.start()
    with prof_mod.RecordEvent("op"):
        pass
    p.stop()
    assert exports == [1]


def test_profiler_chrome_trace_export_content(tmp_path):
    import json as _json
    from paddle_tpu import profiler as prof_mod
    p = prof_mod.Profiler(timer_only=True).start()
    with prof_mod.RecordEvent("fwd"):
        with prof_mod.RecordEvent("attn"):
            pass
    p.step()
    path = p.export(str(tmp_path / "trace.json"))
    p.stop()
    trace = _json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "fwd" in names and "attn" in names and "ProfileStep#0" in names
    fwd = next(e for e in trace["traceEvents"] if e["name"] == "fwd")
    attn = next(e for e in trace["traceEvents"] if e["name"] == "attn")
    # chrome trace units are microseconds; nesting must be containment
    assert fwd["dur"] >= attn["dur"] >= 0
    assert fwd["ts"] <= attn["ts"]
    with pytest.raises(ValueError):
        p.export(str(tmp_path / "x.bin"), format="proto")


def test_summary_table():
    from paddle_tpu import profiler as prof_mod
    p = prof_mod.Profiler(timer_only=True).start()
    with prof_mod.RecordEvent("x"):
        pass
    table = p.summary()
    assert "x" in table and "Calls" in table
    p.stop()


class TestHigherOrderAD:
    """paddle.autograd.jacobian/hessian + incubate jvp/vjp parity."""

    def test_jacobian_modes(self):
        import jax.numpy as jnp
        from paddle_tpu import autograd as ag

        f = lambda x: jnp.stack([x[0] ** 2, x[0] * x[1], x[1] ** 3])
        x = jnp.array([2.0, 3.0])
        expect = np.array([[4.0, 0.0], [3.0, 2.0], [0.0, 27.0]])
        np.testing.assert_allclose(ag.jacobian(f, x, mode="rev"), expect)
        np.testing.assert_allclose(ag.jacobian(f, x, mode="fwd"), expect)
        xb = jnp.stack([x, 2 * x])
        jb = ag.jacobian(f, xb, batch_axis=0)
        assert jb.shape == (2, 3, 2)

    def test_hessian(self):
        import jax.numpy as jnp
        from paddle_tpu import autograd as ag

        f = lambda x: (x[0] ** 2 * x[1] + x[1] ** 3)
        H = ag.hessian(f, jnp.array([1.0, 2.0]))
        np.testing.assert_allclose(H, [[4.0, 2.0], [2.0, 12.0]])

    def test_jvp_vjp(self):
        import jax.numpy as jnp
        from paddle_tpu import autograd as ag

        f = lambda x: jnp.sin(x).sum()
        x = jnp.array([0.0, jnp.pi / 2])
        out, tangent = ag.jvp(f, x, jnp.array([1.0, 1.0]))
        np.testing.assert_allclose(float(tangent), 1.0, atol=1e-6)
        out, grads = ag.vjp(f, x)
        np.testing.assert_allclose(np.asarray(grads),
                                   np.cos(np.asarray(x)), atol=1e-6)


class TestRegularizer:
    def test_l2_decay_equals_scalar(self):
        import jax.numpy as jnp
        from paddle_tpu import optimizer, regularizer

        p = {"w": jnp.ones((4,))}
        g = {"w": jnp.zeros((4,))}
        o1 = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5)
        o2 = optimizer.AdamW(learning_rate=0.1,
                             weight_decay=regularizer.L2Decay(0.5))
        s1, s2 = o1.init(p), o2.init(p)
        p1, _ = o1.apply(g, s1, p)
        p2, _ = o2.apply(g, s2, p)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]))
        assert (np.asarray(p1["w"]) < 1.0).all()  # decay applied

    def test_l1_decay_signs(self):
        import jax.numpy as jnp
        from paddle_tpu import optimizer, regularizer

        p = {"w": jnp.array([2.0, -2.0])}
        g = {"w": jnp.zeros((2,))}
        o = optimizer.SGD(learning_rate=0.1,
                          weight_decay=regularizer.L1Decay(1.0))
        new_p, _ = o.apply(g, o.init(p), p)
        # grad = sign(w): both move toward zero by lr * 1.0
        np.testing.assert_allclose(np.asarray(new_p["w"]), [1.9, -1.9],
                                   atol=1e-6)


class TestCppExtension:
    def test_inline_build_and_call(self, tmp_path):
        import ctypes

        from paddle_tpu.utils import cpp_extension

        src = """
        extern "C" long long mulsum(const long long* a, int n) {
            long long s = 0;
            for (int i = 0; i < n; ++i) s += a[i] * a[i];
            return s;
        }
        """
        lib = cpp_extension.load("testext", [src],
                                 build_directory=str(tmp_path))
        lib.mulsum.restype = ctypes.c_longlong
        arr = (ctypes.c_longlong * 4)(1, 2, 3, 4)
        assert lib.mulsum(arr, 4) == 30
        # cache hit: same source loads without rebuild
        lib2 = cpp_extension.load("testext", [src],
                                  build_directory=str(tmp_path))
        lib2.mulsum.restype = ctypes.c_longlong
        assert lib2.mulsum(arr, 4) == 30

    def test_build_error_surfaces(self, tmp_path):
        from paddle_tpu.utils import cpp_extension
        with pytest.raises(RuntimeError, match="build failed"):
            cpp_extension.load("bad", ["int broken(\n"],
                               build_directory=str(tmp_path))


class TestAmpDebugging:
    def test_check_numerics_eager(self):
        import jax.numpy as jnp
        from paddle_tpu.amp import debugging as dbg

        x = jnp.ones((4,))
        assert dbg.check_numerics(x, "ok") is x
        bad = x.at[1].set(jnp.nan).at[2].set(jnp.inf)
        with pytest.raises(FloatingPointError, match="after attn.*1 NaN"):
            dbg.check_numerics(bad, "after attn")

    def test_check_numerics_traced(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.amp import debugging as dbg

        @jax.jit
        def f(x):
            return dbg.check_numerics(x * 2, "traced")

        np.testing.assert_allclose(np.asarray(f(jnp.ones(3))), 2.0)
        with pytest.raises(Exception, match="traced"):
            jax.block_until_ready(f(jnp.full((3,), jnp.nan)))

    def test_tensor_checker_toggles_debug_nans(self):
        import jax
        from paddle_tpu.amp import debugging as dbg

        cfg = dbg.enable_tensor_checker()
        try:
            assert jax.config.jax_debug_nans
        finally:
            dbg.disable_tensor_checker()
        assert not jax.config.jax_debug_nans


class TestDlpack:
    def test_torch_roundtrip(self):
        import torch

        import jax.numpy as jnp
        from paddle_tpu.utils import dlpack

        t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        arr = dlpack.from_dlpack(t)
        np.testing.assert_array_equal(np.asarray(arr),
                                      t.numpy())
        back = torch.from_dlpack(dlpack.to_dlpack(jnp.ones((4,))))
        np.testing.assert_array_equal(back.numpy(), np.ones(4))


class TestSetDeviceMigration:
    def test_gpu_name_falls_back_with_warning(self):
        import warnings

        import jax

        import paddle_tpu as pt

        try:
            jax.devices("gpu")
            pytest.skip("host actually has a GPU backend")
        except RuntimeError:
            pass
        before = pt.core.get_device()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            try:
                dev = pt.core.set_device("gpu:0")
                assert dev.platform in ("cpu", "tpu")
                assert any("no gpu on this host" in str(x.message).lower()
                           for x in w)
                # fallback path clamps out-of-range indices silently
                assert pt.core.set_device("gpu:99").platform == dev.platform
            finally:
                pt.core.set_device(before)

    def test_native_out_of_range_still_raises(self):
        import jax

        import paddle_tpu as pt

        n = len(jax.devices())
        with pytest.raises(IndexError):
            pt.core.set_device(f"{jax.devices()[0].platform}:{n + 5}")

    def test_unknown_platform_still_raises(self):
        import paddle_tpu as pt
        with pytest.raises(RuntimeError):
            pt.core.set_device("quantum:0")


class TestQuantSparseAudioRound2:
    def test_channelwise_fake_quant(self):
        from paddle_tpu.quantization import (FakeQuanterChannelWiseAbsMax,
                                             FakeQuanterWithAbsMax)
        w = jnp.asarray(np.random.default_rng(0).normal(
            size=(4, 8)).astype(np.float32)) * jnp.asarray(
                [[0.01], [1.0], [100.0], [0.1]])
        cw = FakeQuanterChannelWiseAbsMax()(w)
        gl = FakeQuanterWithAbsMax()(w)
        # per-channel scales keep the small-magnitude rows accurate where
        # one global scale destroys them
        small_err_cw = float(jnp.abs(cw[0] - w[0]).max())
        small_err_gl = float(jnp.abs(gl[0] - w[0]).max())
        assert small_err_cw < small_err_gl / 10

    def test_moving_average_observer(self):
        from paddle_tpu.quantization import MovingAverageAbsmaxObserver
        obs = MovingAverageAbsmaxObserver(moving_rate=0.5)
        obs(jnp.full((3,), 4.0))
        assert float(obs.absmax) == 4.0          # first sees the value
        obs(jnp.full((3,), 8.0))
        assert float(obs.absmax) == 6.0          # 0.5*4 + 0.5*8

    def test_sparse_unary_and_softmax(self):
        from paddle_tpu import sparse as S
        t = S.sparse_coo_tensor([[0, 0, 1], [0, 2, 1]], [1.0, 2.0, 3.0],
                                (2, 3))
        np.testing.assert_allclose(
            np.asarray(S.sqrt(t).to_dense()),
            np.sqrt(np.asarray(t.to_dense())), rtol=1e-5)
        d = np.asarray(S.softmax(t).to_dense())
        # softmax over stored values per row; structural zeros untouched
        np.testing.assert_allclose(d[0, 0] + d[0, 2], 1.0, rtol=1e-5)
        assert d[0, 1] == 0.0 and d[1, 1] == 1.0
        assert S.transpose(t, [1, 0]).shape == (3, 2)

    def test_audio_mfcc_pipeline(self):
        from paddle_tpu import audio
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(2, 8000)).astype(np.float32))
        mf = audio.MFCC(n_mfcc=13, n_fft=400)(x)
        assert mf.shape[0] == 2 and mf.shape[1] == 13
        lm = audio.LogMelSpectrogram(n_fft=400, top_db=80.0)(x)
        assert np.isfinite(np.asarray(lm)).all()
        # dB scaling: max at 0 relative to ref=max when top_db caps range
        assert float(jnp.max(lm) - jnp.min(lm)) <= 80.0 + 1e-3

    def test_mfcc_matches_torchaudio_dct(self):
        from paddle_tpu.audio import create_dct
        try:
            import torchaudio
        except ImportError:
            pytest.skip("torchaudio not installed")
        import torch
        ours = np.asarray(create_dct(13, 64))
        ref = torchaudio.functional.create_dct(13, 64, "ortho").numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
