"""The standing CI gates (tools/ci.py) run as part of the suite, so an
API removal, a hot-op perf cliff, or a sharding-memory regression fails
``pytest`` instead of surfacing in production.

Reference: the reference repo's CI jobs (SURVEY §2.8 — API-approval diff,
op-benchmark, memory checks) — VERDICT r3 weak #2 demanded these become
tests, not scripts nothing runs.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CI = os.path.join(REPO, "tools", "ci.py")


def _run_gate(name, timeout):
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           # the pytest process may hold libtpu (compile-only topologies
           # in test_memproof_dcn); let the gate subprocess load it too
           "ALLOW_MULTIPLE_LIBTPU_LOAD": "1"}
    r = subprocess.run([sys.executable, CI, "--only", name], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=timeout)
    assert r.returncode == 0, f"{name} gate failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


def test_api_compat_gate():
    """Deleting or re-signaturing a recorded public API fails the suite."""
    out = _run_gate("api-compat", timeout=600)
    assert "api-compat gate OK" in out


def test_memproof_lite_gate():
    """The 13B hybrid sharding's per-chip argument bytes still match the
    compiler-proven docs/memproof.json record (a broken ZeRO/TP/amp spec
    shows up as tens of percent drift; tolerance is 5%)."""
    out = _run_gate("memproof-lite", timeout=900)
    assert "memproof-lite gate OK" in out


def test_op_benchmark_gate():
    """Hot ops stay within 2.5x of the recorded CPU baseline — loose
    enough for CI noise, tight enough to catch an op falling off its
    compiled path (interpret-mode Pallas, accidental materialization)."""
    out = _run_gate("op-benchmark", timeout=1500)
    assert "op-benchmark gate OK" in out


def test_lint_gate():
    """pdtpu-lint (paddle_tpu/analysis) runs clean over the whole tree:
    zero non-baselined findings across the six invariant rules
    (donation/compat/zero-overhead/retrace/fault-site/lock), jax-free
    and in seconds (docs/ANALYSIS.md; fast path:
    ``python tools/ci.py --only lint``)."""
    out = _run_gate("lint", timeout=300)
    assert "lint gate OK" in out
    assert "0 new finding(s)" in out
    assert "(jax imported: False)" in out


def test_telemetry_overhead_gate():
    """The disabled-observability TrainStep dispatch stays one falsy
    check: registry/sink calls are poisoned and the per-call cost is
    bounded (tools/ci.py gate_telemetry_overhead)."""
    out = _run_gate("telemetry-overhead", timeout=300)
    assert "telemetry-overhead gate OK" in out


def test_chaos_gate():
    """Resilience end-to-end (tools/ci.py gate_chaos): with a fault
    injected at every registered site, the supervised train run finishes
    with params bitwise-equal to the fault-free run; with the newest
    checkpoint corrupted, resume falls back to the previous valid one
    and still reproduces the baseline."""
    out = _run_gate("chaos", timeout=900)
    assert "chaos gate OK" in out


def test_serving_smoke_gate():
    """The continuous-batching engine's contracts (tools/ci.py
    gate_serving_smoke): mixed-length requests joining/leaving the
    running batch trigger zero recompiles after warmup, and every KV
    block is reclaimed at drain (docs/SERVING.md)."""
    out = _run_gate("serving-smoke", timeout=600)
    assert "serving-smoke gate OK" in out
    assert "0 compiles after warmup" in out


def test_chaos_serving_gate():
    """Serving-path resilience (tools/ci.py gate_chaos_serving): with a
    PDTPU_FAULTS plan firing at every serving site during a mixed churn
    run with preemption and CoW, the engine never tears down the
    compiled step, reclaims every KV block at drain, and greedy outputs
    stay token-identical to the fault-free run (docs/RESILIENCE.md)."""
    out = _run_gate("chaos-serving", timeout=900)
    assert "chaos-serving gate OK" in out
    assert "token-identical to the fault-free run" in out


def test_serving_dist_gate():
    """Sharded serving (tools/ci.py gate_serving_dist): on the forced
    8-device CPU mesh, a TP=2 engine serves greedy outputs
    token-identical to the single-chip engine with zero compiles after
    warmup, and a 2-replica DP set behind the FrontDoor survives an
    injected serve.replica fault with every in-flight request re-queued
    and completed (docs/SERVING.md "Sharded serving")."""
    out = _run_gate("serving-dist", timeout=1500)
    assert "serving-dist gate OK" in out
    assert "token-identical to single-chip" in out
    assert "survived an injected replica fault" in out


def test_serving_disagg_gate():
    """Disaggregated serving (tools/ci.py gate_serving_disagg): 2
    prefill + 2 decode replicas stream KV pages over a TCPStore
    transport through injected serve.xfer.* faults (transient retried,
    hard burst degraded to re-prefill) and a decode-replica kill, with
    greedy outputs token-identical to a colocated run, zero compiles,
    all blocks reclaimed, and every trace timeline complete with an
    xfer segment (docs/SERVING.md "Disaggregated serving")."""
    out = _run_gate("serving-disagg", timeout=1200)
    assert "serving-disagg gate OK" in out
    assert "token-identical to the colocated run" in out
    assert "decode-replica kill" in out


@pytest.mark.slow
def test_serving_cluster_gate():
    """Cluster control plane (tools/ci.py gate_serving_cluster): 2
    prefill + 2 decode ``serving.worker`` OS processes under
    epoch-fenced leases survive a mid-churn SIGKILL (lease-expiry
    evacuation), a forced role flip, and injected ``cluster.*`` faults
    in every worker — greedy outputs token-identical to a colocated
    run, zero compiles after warmup, all blocks reclaimed, zero lease
    losses on the survivors (docs/SERVING.md "Cluster serving").
    Phase B SIGKILLs the CONTROLLER: a standby takes over off the
    stale ``ControllerLease``, replays the admission journal, answers
    every re-submitted idempotency key with the same rid, and a
    ``ClusterGateway`` smoke proves SSE/dup/drain semantics over the
    takeover winner."""
    out = _run_gate("serving-cluster", timeout=1800)
    assert "serving-cluster gate OK" in out
    assert "token-identical to the colocated run" in out
    assert "SIGKILL" in out and "role flip" in out
    assert "standby controller takeover" in out
    assert "zero duplicates" in out
    assert "drain answered the typed 503" in out


def test_bench_regression_gate():
    """Perf-regression ledger (tools/ci.py gate_bench_regression):
    bench_compare --check must PASS on the committed baseline's own
    seed numbers and FAIL on an injected 2x CPU-plumbing slowdown —
    both proven through the CLI exit code, so a broken comparator is as
    loud as a broken bench (docs/BENCH.md "Trajectory")."""
    out = _run_gate("bench-regression", timeout=300)
    assert "bench-regression gate OK" in out
    assert "seed run → rc=0" in out
    assert "slowed-2x run → rc=1" in out


def test_api_compat_rejects_foreign_module_leak(monkeypatch):
    """A leaked implementation import (jax/os/...) reachable as a public
    attribute hard-fails collect() (VERDICT r4 weak #1: the gate must
    reject module-typed entries, not lock them in)."""
    import os as _os
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import check_api_compat as gate

    import paddle_tpu.amp as amp
    monkeypatch.setattr(amp, "__all__", list(amp.__all__) + ["leaked_mod"],
                        raising=True)
    monkeypatch.setattr(amp, "leaked_mod", _os, raising=False)
    with pytest.raises(SystemExit) as e:
        gate.collect()
    assert e.value.code == 3
