"""Multi-tenant front door + streaming server (paddle_tpu.serving).

The production story on top of the engine: typed shed answers with
retry-after, token-bucket rate limits per tenant, strict-priority +
weighted-DRR fairness, preemption under pool pressure, and the stdlib
HTTP server with graceful SIGTERM drain.  Everything deterministic:
buckets run on an injected clock, and greedy outputs stay
token-identical through every admission decision.
"""

import http.client
import json
import os
import signal
import threading

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import serving
from paddle_tpu.serving import (Admission, FrontDoor, ServingServer,
                                TenantPolicy, TokenBucket)

R = np.random.default_rng(0)


def _prompt(n):
    return R.integers(0, 256, size=n).astype(np.int32)


def _ref(model, p, m):
    return np.asarray(model.generate(
        jnp.asarray(p)[None], max_new_tokens=m,
        temperature=0.0))[0, len(p):]


@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


class TestTokenBucket:
    def test_deterministic_refill_and_wait(self):
        t = [0.0]
        b = TokenBucket(rate=10.0, capacity=20.0, clock=lambda: t[0])
        assert b.try_take(15) == 0.0
        wait = b.try_take(10)              # level 5, short by 5
        assert wait == pytest.approx(0.5)
        t[0] += 0.5
        assert b.try_take(10) == 0.0
        assert TokenBucket(0.0, 1.0, clock=lambda: t[0]).try_take(2) \
            == float("inf")


class TestFrontDoorShedding:
    def test_shed_then_retry_after_flow(self, tiny_llama):
        """The overload contract: a shed is a TYPED ANSWER with a
        retry-after hint, not an exception — and retrying after the
        drain is admitted."""
        eng = serving.Engine(tiny_llama, max_batch=2, max_seq_len=32,
                             page_size=8).warmup()
        door = FrontDoor(eng, max_queue_depth=3)
        admitted = [door.submit(_prompt(4), max_new_tokens=3)
                    for _ in range(8)]
        sheds = [a for a in admitted if not a.admitted]
        assert sheds and all(a.reason == "queue_full" for a in sheds)
        assert all(a.retry_after_s > 0 for a in sheds)
        assert all(a.request_id is None for a in sheds)
        outs = door.run()
        assert len(outs) == sum(a.admitted for a in admitted)
        assert eng.kv_blocks_used == 0
        retry = door.submit(_prompt(4), max_new_tokens=3)
        assert retry.admitted                 # the hint was honest
        door.run()

    def test_rate_limit_with_injected_clock(self, tiny_llama):
        eng = serving.Engine(tiny_llama, max_batch=2, max_seq_len=32,
                             page_size=8).warmup()
        t = [0.0]
        door = FrontDoor(eng, policies={
            "free": TenantPolicy(rate_tokens_per_s=1.0,
                                 burst_tokens=10.0)},
            clock=lambda: t[0])
        a1 = door.submit(_prompt(4), tenant="free", max_new_tokens=4)
        assert a1.admitted
        a2 = door.submit(_prompt(4), tenant="free", max_new_tokens=4)
        assert not a2.admitted and a2.reason == "rate_limited"
        assert a2.retry_after_s >= 6          # 8 tokens short at 1/s
        t[0] += a2.retry_after_s              # wait as told → admitted
        a3 = door.submit(_prompt(4), tenant="free", max_new_tokens=4)
        assert a3.admitted
        door.run()
        assert eng.kv_blocks_used == 0

    def test_quota_and_budget_sheds(self, tiny_llama):
        eng = serving.Engine(tiny_llama, max_batch=1, max_seq_len=32,
                             page_size=8).warmup()
        door = FrontDoor(eng, policies={
            "q": TenantPolicy(max_live_requests=1)})
        assert door.submit(_prompt(3), tenant="q",
                           max_new_tokens=8).admitted
        a = door.submit(_prompt(3), tenant="q", max_new_tokens=8)
        assert not a.admitted and a.reason == "quota"
        b = door.submit(_prompt(40), max_new_tokens=8)   # never fits
        assert not b.admitted and b.reason == "budget"
        assert b.retry_after_s is None        # retrying cannot help
        door.run()
        assert door.submit(_prompt(3), tenant="q",
                           max_new_tokens=8).admitted   # quota released
        door.run()

    def test_raise_on_shed_typed_exceptions(self, tiny_llama):
        eng = serving.Engine(tiny_llama, max_batch=1, max_seq_len=32,
                             page_size=8).warmup()
        t = [0.0]
        door = FrontDoor(eng, policies={
            "free": TenantPolicy(rate_tokens_per_s=1.0,
                                 burst_tokens=5.0)},
            max_queue_depth=2, clock=lambda: t[0])
        assert door.submit(_prompt(3), tenant="free",
                           max_new_tokens=2).admitted
        with pytest.raises(serving.RateLimited) as e:
            door.submit(_prompt(3), tenant="free", max_new_tokens=2,
                        raise_on_shed=True)
        assert e.value.retry_after_s > 0
        assert door.submit(_prompt(3), tenant="other",
                           max_new_tokens=2).admitted   # depth now 2
        with pytest.raises(serving.QueueFull):
            door.submit(_prompt(3), tenant="other", max_new_tokens=2,
                        raise_on_shed=True)
        door.run()

    def test_rate_bucket_not_charged_for_other_sheds(self, tiny_llama):
        """Review fix: the token bucket is the LAST gate — a request
        shed for queue_full must not burn the tenant's tokens, and a
        cost beyond burst capacity sheds as budget (a finite
        retry-after would be a lie: the level can never reach it)."""
        eng = serving.Engine(tiny_llama, max_batch=1, max_seq_len=32,
                             page_size=8).warmup()
        t = [0.0]
        door = FrontDoor(eng, policies={
            "free": TenantPolicy(rate_tokens_per_s=1.0,
                                 burst_tokens=6.0)},
            max_queue_depth=1, clock=lambda: t[0])
        assert door.submit(_prompt(4), max_new_tokens=2).admitted
        # queue now full: these shed BEFORE touching free's bucket
        for _ in range(5):
            a = door.submit(_prompt(4), tenant="free", max_new_tokens=2)
            assert a.reason == "queue_full"
        door.run()
        a = door.submit(_prompt(4), tenant="free", max_new_tokens=2)
        assert a.admitted, a                  # bucket was never charged
        door.run()
        t[0] += 10.0                          # refill for the next probe
        b = door.submit(_prompt(4), tenant="free", max_new_tokens=8)
        assert not b.admitted and b.reason == "budget"   # 12 > burst 6
        assert b.retry_after_s is None
        door.run()
        assert eng.kv_blocks_used == 0

    def test_slo_ttft_backpressure_sheds_low_priority(self, tiny_llama):
        """With the TTFT p95 signal over its SLO, tenants below the
        priority floor shed (reason slo_shed) while protected tenants
        keep being admitted — the telemetry-driven decision."""
        import paddle_tpu.observability as obs
        tel = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        try:
            eng = serving.Engine(tiny_llama, max_batch=2, max_seq_len=32,
                                 page_size=8).warmup()
            door = FrontDoor(eng, policies={
                "lo": TenantPolicy(priority=0),
                "hi": TenantPolicy(priority=1)},
                slo_ttft_p95_ms=0.000001)     # any real TTFT breaches
            assert door.submit(_prompt(3), tenant="lo",
                               max_new_tokens=2).admitted
            door.run()                        # populates serve.ttft_ms
            a = door.submit(_prompt(3), tenant="lo", max_new_tokens=2)
            assert not a.admitted and a.reason == "slo_shed"
            assert a.retry_after_s > 0
            b = door.submit(_prompt(3), tenant="hi", max_new_tokens=2)
            assert b.admitted                 # protected tier unaffected
            door.run()
            assert tel.registry.snapshot()["serve.shed"] == 1
            shed_evs = tel.sinks[0].events("serve_shed")
            assert shed_evs and shed_evs[0]["tenant"] == "lo" \
                and shed_evs[0]["reason"] == "slo_shed"
        finally:
            obs.disable()


class TestFairness:
    def test_high_priority_not_starved_by_flood(self, tiny_llama):
        """A flood of low-priority work queued ahead must not starve a
        high-priority tenant: strict tiers admit its requests next."""
        model = tiny_llama
        eng = serving.Engine(model, max_batch=2, max_seq_len=32,
                             page_size=8).warmup()
        door = FrontDoor(eng, policies={
            "lo": TenantPolicy(priority=0),
            "hi": TenantPolicy(priority=1)}, max_queue_depth=64)
        finish_order = []
        lo = [door.submit(_prompt(4), tenant="lo",
                          max_new_tokens=4).request_id
              for _ in range(10)]
        hi = [door.submit(_prompt(4), tenant="hi",
                          max_new_tokens=4).request_id
              for _ in range(2)]
        for ev in door.stream():
            if ev.finished:
                finish_order.append(ev.request_id)
        assert set(finish_order) == set(lo + hi)
        assert eng.kv_blocks_used == 0
        # the hi requests (submitted LAST, behind 10 queued lo) finish
        # before the tail of the flood
        last_lo_positions = sorted(finish_order.index(r) for r in lo)[-4:]
        for r in hi:
            assert finish_order.index(r) < last_lo_positions[0], \
                (finish_order, r)
        # greedy outputs unaffected by the reordering
        for rid in lo + hi:
            assert len(eng.output_ids(rid)) == 4

    def test_weighted_drr_within_a_tier(self, tiny_llama):
        """Two equal-priority floods under contention split engine
        admissions by weight, not by arrival order: the 2x-weight
        tenant lands ~2x the admissions once both queues contend, and
        the 1x tenant is not starved."""
        eng = serving.Engine(tiny_llama, max_batch=3, max_seq_len=32,
                             page_size=8).warmup()
        door = FrontDoor(eng, policies={
            "a": TenantPolicy(weight=2.0), "b": TenantPolicy(weight=1.0)},
            max_queue_depth=64, drr_quantum=4)
        order = []
        orig = eng.add_request

        def tracking(*a, **kw):
            order.append(kw.get("tenant"))
            return orig(*a, **kw)

        eng.add_request = tracking
        # b's flood arrives FIRST: pure FIFO would drain all of b before
        # any of a.  Staging (3 deep) takes the head of b's flood, the
        # rest contends through DRR.
        for _ in range(6):
            door.submit(_prompt(4), tenant="b", max_new_tokens=2)
        for _ in range(6):
            door.submit(_prompt(4), tenant="a", max_new_tokens=2)
        door.run()
        assert eng.kv_blocks_used == 0
        assert order[:3] == ["b", "b", "b"]   # pre-contention staging
        contended = order[3:9]                # both queues nonempty here
        assert contended.count("a") > contended.count("b") >= 1, order

    def test_preemption_under_pool_pressure(self, tiny_llama):
        """A block-starved high-priority admission preempts the
        lowest-priority victim (swap to host) instead of waiting out
        its whole decode — and the victim still completes
        token-identical afterwards."""
        model = tiny_llama
        eng = serving.Engine(model, max_batch=2, max_seq_len=32,
                             page_size=8, num_blocks=4).warmup()
        door = FrontDoor(eng, policies={
            "lo": TenantPolicy(priority=0),
            "hi": TenantPolicy(priority=1)})
        p_lo, p_hi = _prompt(9), _prompt(11)
        lo = door.submit(p_lo, tenant="lo", max_new_tokens=12)
        door.step(); door.step()              # lo occupies 3 of 4 blocks
        hi = door.submit(p_hi, tenant="hi", max_new_tokens=12)
        door.step()                           # pressure → lo preempted
        st_lo = eng._states[lo.request_id]
        assert st_lo.preempts == 1
        outs = door.run()
        assert np.array_equal(_ref(model, p_hi, 12),
                              np.asarray(outs[hi.request_id]))
        assert np.array_equal(_ref(model, p_lo, 12),
                              np.asarray(outs[lo.request_id]))
        assert eng.kv_blocks_used == 0

    def test_no_preemption_within_same_priority(self, tiny_llama):
        eng = serving.Engine(tiny_llama, max_batch=2, max_seq_len=32,
                             page_size=8, num_blocks=4).warmup()
        door = FrontDoor(eng)                 # everyone default priority
        r1 = door.submit(_prompt(9), max_new_tokens=12)
        door.step(); door.step()
        door.submit(_prompt(11), max_new_tokens=12)
        door.step(); door.step()
        assert eng._states[r1.request_id].preempts == 0   # FIFO waits
        door.run()
        assert eng.kv_blocks_used == 0


class TestServingServer:
    def _post(self, conn, body):
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r, r.read()

    def test_server_smoke_request_stream_drain(self, tiny_llama):
        """The satellite smoke test: request in → streamed tokens out
        (token-identical to generate()) → graceful drain → every KV
        block reclaimed."""
        model = tiny_llama
        eng = serving.Engine(model, max_batch=2, max_seq_len=64,
                             page_size=8).warmup()
        srv = ServingServer(eng, port=0)
        host, port = srv.start()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200 \
                and json.loads(r.read())["status"] == "serving"

            p = _prompt(6)
            ref = _ref(model, p, 5).tolist()
            r, raw = self._post(conn, {"prompt": p.tolist(),
                                       "max_tokens": 5})
            assert r.status == 200
            out = json.loads(raw)
            assert out["choices"][0]["token_ids"] == ref
            assert out["choices"][0]["finish_reason"] == "length"
            assert out["usage"]["completion_tokens"] == 5

            r, raw = self._post(conn, {"prompt": p.tolist(),
                                       "max_tokens": 4, "stream": True})
            assert r.status == 200
            assert r.getheader("Content-Type") == "text/event-stream"
            toks, done = [], False
            for line in raw.decode().splitlines():
                if line == "data: [DONE]":
                    done = True
                elif line.startswith("data: "):
                    toks.append(
                        json.loads(line[6:])["choices"][0]["token_id"])
            assert done and toks == ref[:4]

            # malformed + draining answers are typed
            r, raw = self._post(conn, {"prompt": "text, no tokenizer"})
            assert r.status == 400
            srv.begin_drain()
            r, raw = self._post(conn, {"prompt": p.tolist(),
                                       "max_tokens": 2})
            assert r.status == 503 and r.getheader("Retry-After")
            assert json.loads(raw)["error"]["type"] == "draining"
            assert srv.wait_drained(timeout=30)
        finally:
            srv.close()
        assert eng.kv_blocks_used == 0

    def test_sigterm_graceful_drain(self, tiny_llama):
        """serve_forever() + SIGTERM (PreemptionGuard): the in-flight
        request completes, the server drains and returns, nothing
        leaks.  Runs serve_forever on the MAIN thread — signal handlers
        can only install there."""
        model = tiny_llama
        eng = serving.Engine(model, max_batch=2, max_seq_len=64,
                             page_size=8).warmup()
        srv = ServingServer(eng, port=0)
        host, port = srv.start()             # bind before the client runs
        p = _prompt(5)
        ref = _ref(model, p, 3).tolist()
        result = {}

        def client():
            try:
                conn = http.client.HTTPConnection(host, port, timeout=60)
                conn.request("POST", "/v1/completions",
                             json.dumps({"prompt": p.tolist(),
                                         "max_tokens": 3}),
                             {"Content-Type": "application/json"})
                result["out"] = json.loads(conn.getresponse().read())
            finally:
                os.kill(os.getpid(), signal.SIGTERM)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        srv.serve_forever()                   # returns after the drain
        t.join(timeout=30)
        assert result["out"]["choices"][0]["token_ids"] == ref
        assert eng.kv_blocks_used == 0

    def test_shed_maps_to_http_status(self, tiny_llama):
        eng = serving.Engine(tiny_llama, max_batch=1, max_seq_len=32,
                             page_size=8).warmup()
        t = [0.0]
        door = FrontDoor(eng, policies={
            "free": TenantPolicy(rate_tokens_per_s=1.0,
                                 burst_tokens=6.0)},
            max_queue_depth=2, clock=lambda: t[0])
        srv = ServingServer(door, port=0)
        host, port = srv.start()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            p = _prompt(3)
            r, _ = self._post(conn, {"prompt": p.tolist(),
                                     "max_tokens": 3, "tenant": "free"})
            assert r.status == 200
            r, raw = self._post(conn, {"prompt": p.tolist(),
                                       "max_tokens": 3,
                                       "tenant": "free"})
            assert r.status == 429 and r.getheader("Retry-After")
            assert json.loads(raw)["error"]["type"] == "rate_limited"
            # a request that can never fit → 400, no Retry-After story
            r, raw = self._post(conn, {"prompt": _prompt(40).tolist(),
                                       "max_tokens": 8})
            assert r.status == 400
            assert json.loads(raw)["error"]["type"] == "budget"
        finally:
            srv.begin_drain()
            srv.wait_drained(timeout=30)
            srv.close()
        assert eng.kv_blocks_used == 0


class TestFrontDoorTelemetry:
    def test_tenant_counters_and_report_fold(self, tiny_llama, tmp_path):
        """serve.tenant[...] counters + shed/preempt events land in the
        registry and telemetry_report folds the new columns."""
        import subprocess
        import sys as _sys

        import paddle_tpu.observability as obs
        path = str(tmp_path / "serve.jsonl")
        tel = obs.enable(jsonl_path=path, crash_hooks=False)
        try:
            eng = serving.Engine(tiny_llama, max_batch=2, max_seq_len=32,
                                 page_size=8).warmup()
            door = FrontDoor(eng, policies={
                "a": TenantPolicy(priority=1)}, max_queue_depth=2)
            rid = door.submit(_prompt(4), tenant="a",
                              max_new_tokens=6).request_id
            door.step(); door.step()
            eng.preempt(rid)
            for _ in range(6):
                door.submit(_prompt(4), tenant="b", max_new_tokens=4)
            door.run()
            snap = tel.registry.snapshot()
            assert snap["serve.tenant[a].requests"] == 1
            assert snap["serve.preemptions"] >= 1
            assert snap["serve.shed"] > 0
            assert snap[
                "serve.shed[queue_full].count"] == snap["serve.shed"]
        finally:
            obs.disable()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [_sys.executable,
             os.path.join(repo, "tools", "telemetry_report.py"),
             "--json", path],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        sv = summary["serving"]
        assert sv["preempts"] >= 1 and sv["restores"] >= 1
        assert sv["sheds"].get("queue_full", 0) > 0
        assert sv["tenants"].get("a") == 1
        assert sv["swapped_pages"] >= 1
