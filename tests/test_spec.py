"""Speculative decoding inside the one compiled serving step
(paddle_tpu.serving.spec + the engine's verify path).

The load-bearing guarantees (docs/SERVING.md "Speculative decoding"):

- greedy outputs are TOKEN-IDENTICAL to the non-speculative engine (and
  therefore to ``model.generate()``) under every composition — chunked
  prefill churn, prefix-cache hits, int8 KV pools, preemption→restore,
  mid-verify faults, TP meshes, DP replica sets;
- ZERO compiles after warmup under draft-hit/draft-miss churn: draft
  length rides the one compiled ``(B, C)`` step as span-length DATA;
- rejection rollback is kv_len bookkeeping only — no frees, no copies;
- temperature streams are reproducible across spec-on/spec-off (PRNG
  keys derive per emitted-token index, never per step);
- acceptance telemetry lands in ``serve.spec.*`` and on ``serve_trace``
  retire events, and the bench plumbing shows > 1 token per verify
  step on a repetitive workload.

Runs on CPU (conftest forces an 8-device virtual mesh for the TP/DP
composition tests).
"""

import os
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu import resilience as rs
from paddle_tpu import serving
from paddle_tpu.serving.spec import NgramProposer

R = np.random.default_rng(0)


def _prompt(n):
    return R.integers(0, 256, size=n).astype(np.int32)


def _motif_prompt(motif_len=5, reps=3, rng=None):
    rng = rng or R
    return np.tile(rng.integers(0, 256, size=motif_len).astype(np.int32),
                   reps)


def _tiny():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


def _engine(model=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(model if model is not None else _tiny(), **kw)


def _serve(eng, prompts, max_new=16, **kw):
    rids = [eng.add_request(p, max_new_tokens=max_new, **kw)
            for p in prompts]
    outs = eng.run()
    return [outs[r] for r in rids]


class _St:
    """Minimal RequestState stand-in for proposer unit tests."""

    def __init__(self, prompt, output=()):
        class _Req:
            pass
        self.request = _Req()
        self.request.request_id = "r0"
        self.request.prompt_ids = np.asarray(prompt, np.int32)
        self.output_ids = list(output)


# ---------------------------------------------------------------------------
# the proposer
# ---------------------------------------------------------------------------

class TestNgramProposer:
    def test_basic_suffix_match(self):
        p = NgramProposer(depth=4)
        #        0  1  2  3  4  5  6  7
        st = _St([1, 2, 3, 9, 8, 1, 2, 3])
        # suffix [1,2,3] matched at position 2 → continuation [9,8,1,2]
        assert p.propose(st, 4) == [9, 8, 1, 2]
        assert p.draft_hits == 1

    def test_longest_ngram_wins(self):
        p = NgramProposer(depth=2, min_ngram=1, max_ngram=3)
        # [5,6] occurs earlier followed by 7; the bare [6] occurs
        # later followed by 0 — the longer match must win
        st = _St([5, 6, 7, 4, 6, 0, 5, 6])
        assert p.propose(st, 2) == [7, 4]

    def test_miss_returns_empty(self):
        p = NgramProposer(depth=4)
        st = _St([1, 2, 3, 4, 5, 6, 7, 8])
        assert p.propose(st, 4) == []
        assert p.draft_misses == 1

    def test_cap_bounds_draft(self):
        p = NgramProposer(depth=8)
        st = _St([1, 2, 3, 9, 8, 7, 6, 1, 2, 3])
        assert len(p.propose(st, 2)) == 2
        assert p.propose(st, 0) == []

    def test_incremental_growth_and_self_match(self):
        p = NgramProposer(depth=3)
        st = _St([4, 4, 4], output=[])
        # the current suffix's own occurrence is never its own match,
        # and the proposer prefers the longest available continuation
        # (the [4]-gram at position 0 drafts two tokens; the [4,4]-gram
        # match would draft one)
        d = p.propose(st, 3)
        assert d == [4, 4]
        st.output_ids.extend([4, 4])
        assert p.propose(st, 3) == [4, 4, 4]

    def test_rollback_rebuilds(self):
        p = NgramProposer(depth=4)
        st = _St([1, 2], output=[3, 1, 2])
        assert p.propose(st, 4) == [3, 1, 2]
        # fault-isolation rewind: output truncated below the watermark
        del st.output_ids[1:]
        d = p.propose(st, 4)      # must not crash or read stale state
        assert isinstance(d, list)

    def test_drop_and_lru_bound(self):
        p = NgramProposer(depth=2, max_requests=2)
        for i in range(4):
            st = _St([1, 2, 1, 2])
            st.request.request_id = f"r{i}"
            p.propose(st, 2)
        assert len(p) == 2        # LRU-bounded
        p.drop("r3")
        assert len(p) == 1
        p.drop("unknown")         # no-op

    def test_validation(self):
        with pytest.raises(ValueError, match="depth"):
            NgramProposer(depth=0)
        with pytest.raises(ValueError, match="min_ngram"):
            NgramProposer(depth=2, min_ngram=3, max_ngram=2)


# ---------------------------------------------------------------------------
# the speculative engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    return _tiny()


@pytest.fixture(scope="module")
def mixed_prompts():
    rng = np.random.default_rng(7)
    return [_motif_prompt(5, 3, rng), _prompt(3), _prompt(17),
            _motif_prompt(4, 4, rng), _prompt(9)]


@pytest.fixture(scope="module")
def baseline(tiny_model, mixed_prompts):
    """Non-speculative greedy outputs for the shared prompt mix."""
    return _serve(_engine(tiny_model).warmup(), mixed_prompts)


class TestSpecEngine:
    def test_greedy_token_identity_and_acceptance(self, tiny_model,
                                                  mixed_prompts,
                                                  baseline):
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        got = _serve(eng, mixed_prompts)
        assert got == baseline
        st = eng.spec_stats()
        assert st["proposed"] > 0 and st["accepted"] > 0
        assert 0.0 < st["accept_rate"] <= 1.0
        assert eng.kv_blocks_used == 0

    def test_draft_depth_widens_span(self, tiny_model):
        eng = _engine(tiny_model, prefill_chunk=2, spec_decode=True,
                      draft_depth=6)
        assert eng.prefill_chunk == 7      # max(chunk, depth + 1)
        with pytest.raises(ValueError, match="draft_depth"):
            _engine(tiny_model, spec_decode=True, draft_depth=0)

    def test_zero_compiles_under_hit_miss_churn(self, tiny_model,
                                                mixed_prompts):
        tel = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        try:
            eng = _engine(tiny_model, spec_decode=True,
                          draft_depth=4).warmup()
            c0 = tel.sentinel.compiles()
            for p in mixed_prompts:          # staggered: churn
                eng.add_request(p, max_new_tokens=12)
                eng.step()
            eng.run()
            assert tel.sentinel.compiles() - c0 == 0
            assert eng._step_fn._cache_size() == 1
            assert eng._cow_fn._cache_size() == 1
        finally:
            obs.disable()

    def test_identity_with_prefix_cache_hits(self, tiny_model):
        common = _prompt(16)                 # 2 full pages
        prompts = [np.concatenate([common, _prompt(t)])
                   for t in (5, 9, 3)] + [common]
        base_eng = _engine(tiny_model)
        base = []
        for p in prompts:                    # serially: later ones hit
            base.extend(_serve(base_eng.warmup() if p is prompts[0]
                               else base_eng, [p], max_new=8))
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        got = []
        for p in prompts:
            got.extend(_serve(eng, [p], max_new=8))
        assert got == base
        assert eng.prefix_stats()["hits"] > 0
        assert eng.kv_blocks_used == 0

    def test_identity_with_int8_pools(self, tiny_model, mixed_prompts):
        base = _serve(_engine(tiny_model,
                              kv_cache_dtype="int8").warmup(),
                      mixed_prompts)
        eng = _engine(tiny_model, kv_cache_dtype="int8",
                      spec_decode=True, draft_depth=4).warmup()
        assert _serve(eng, mixed_prompts) == base
        assert eng.spec_stats()["proposed"] > 0

    def test_identity_across_preemption(self, tiny_model):
        prompts = [_motif_prompt(5, 3, np.random.default_rng(3)),
                   _prompt(9)]
        base = _serve(_engine(tiny_model, spec_decode=True,
                              draft_depth=4).warmup(), prompts,
                      max_new=14)
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        rids = [eng.add_request(p, max_new_tokens=14) for p in prompts]
        for _ in range(4):
            eng.step()
        # preempt a DECODING slot mid-speculation: the swap must round-
        # trip exactly the accepted prefix (kv_len), nothing speculative
        victim = None
        for _ in range(40):
            for _slot, st in eng.scheduler.active():
                if not st.prefilling:
                    victim = st.request.request_id
                    break
            if victim is not None:
                break
            eng.step()
        assert victim is not None and eng.preempt(victim)
        eng.run()
        assert [eng.output_ids(r) for r in rids] == base
        assert eng.kv_blocks_used == 0

    def test_mid_verify_fault_rolls_back_token_identical(
            self, tiny_model, mixed_prompts):
        base = _serve(_engine(tiny_model, spec_decode=True,
                              draft_depth=4).warmup(), mixed_prompts)
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        rs.clear_faults()
        rs.install_faults("serve.step@2x2")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                got = _serve(eng, mixed_prompts)
        finally:
            rs.clear_faults()
        assert got == base
        assert eng.kv_blocks_used == 0

    def test_draft_fault_degrades_not_isolates(self, tiny_model,
                                               mixed_prompts, baseline):
        """A serve.spec fault costs that slot its draft for the step —
        never the request, never an isolation."""
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        rs.clear_faults()
        rs.install_faults("serve.spec@0x3")
        try:
            got = _serve(eng, mixed_prompts)
        finally:
            rs.clear_faults()
        assert got == baseline
        assert eng.spec_stats()["errors"] == 3

    def test_temperature_stream_reproducible_spec_on_off(self,
                                                         tiny_model):
        """The PRNG satellite: keys derive per emitted-token index, so
        the sampled stream is invariant to how many tokens each step
        accepted — spec-on and spec-off engines draw identical
        temperature streams."""
        p = _prompt(6)

        def stream(spec):
            eng = _engine(tiny_model, spec_decode=spec, seed=11).warmup()
            rid = eng.add_request(p, max_new_tokens=10, temperature=0.9)
            eng.run()
            return eng.output_ids(rid)

        a, b = stream(False), stream(True)
        assert a == b
        assert len(set(a)) > 1       # actually sampling, not degenerate

    def test_duplicate_prompts_sample_distinct_streams(self, tiny_model):
        """Best-of-n must not collapse: the per-request seed folds the
        submission ordinal, so identical prompts submitted to one
        engine draw DIFFERENT temperature streams — while re-driving
        an identical engine the same way reproduces both."""
        p = _prompt(6)

        def streams():
            eng = _engine(tiny_model, seed=3).warmup()
            rids = [eng.add_request(p, max_new_tokens=8, temperature=0.9)
                    for _ in range(3)]
            eng.run()
            return [eng.output_ids(r) for r in rids]

        a, b = streams(), streams()
        assert a == b                      # reproducible per engine
        assert len({tuple(s) for s in a}) > 1   # but not collapsed

    def test_temperature_slots_never_draft(self, tiny_model):
        eng = _engine(tiny_model, spec_decode=True, draft_depth=4).warmup()
        rid = eng.add_request(_motif_prompt(4, 4), max_new_tokens=10,
                              temperature=0.8)
        eng.run()
        assert len(eng.output_ids(rid)) == 10
        assert eng.spec_stats()["proposed"] == 0

    def test_eos_mid_acceptance_truncates(self, tiny_model):
        """An accepted draft token that IS the eos finishes the request
        there — the rest of the accepted span is dropped, exactly like
        the one-token-at-a-time engine would have stopped."""
        p = _motif_prompt(5, 3, np.random.default_rng(5))
        ref = _serve(_engine(tiny_model).warmup(), [p], max_new=16)[0]
        eos = ref[len(ref) // 2]             # a token mid-stream
        base = _serve(_engine(tiny_model).warmup(), [p], max_new=16,
                      eos_token_id=int(eos))[0]
        got = _serve(_engine(tiny_model, spec_decode=True,
                             draft_depth=4).warmup(), [p], max_new=16,
                     eos_token_id=int(eos))[0]
        assert got == base
        assert got[-1] == eos

    def test_tight_budget_caps_draft(self, tiny_model):
        """max_new_tokens=2: at most 1 draft ever makes sense, and the
        speculative engine must not overshoot the budget."""
        prompts = [_motif_prompt(5, 3), _prompt(7)]
        base = _serve(_engine(tiny_model).warmup(), prompts, max_new=2)
        got = _serve(_engine(tiny_model, spec_decode=True,
                             draft_depth=4).warmup(), prompts, max_new=2)
        assert got == base
        assert all(len(o) == 2 for o in got)

    def test_spec_off_by_default(self, tiny_model):
        eng = _engine(tiny_model)
        assert eng.spec is None and eng.draft_depth == 0
        assert eng.spec_stats()["proposed"] == 0


# ---------------------------------------------------------------------------
# composition: TP meshes and DP replica sets
# ---------------------------------------------------------------------------

class TestSpecSharded:
    def test_tp2_token_identity(self, tiny_model, mixed_prompts,
                                baseline):
        mesh = serving.serving_mesh(tp=2)
        eng = serving.Engine(_tiny(), max_batch=4, max_seq_len=96,
                             page_size=8, prefill_chunk=8, mesh=mesh,
                             spec_decode=True, draft_depth=4).warmup()
        got = _serve(eng, mixed_prompts)
        assert got == baseline
        assert eng.spec_stats()["accepted"] > 0
        assert eng.kv_blocks_used == 0

    def test_replica_set_aggregate_stats_and_identity(self,
                                                      mixed_prompts,
                                                      baseline):
        rset = serving.EngineReplicaSet(
            [_engine(spec_decode=True, draft_depth=4)
             for _ in range(2)]).warmup()
        rids = [rset.add_request(p, max_new_tokens=16)
                for p in mixed_prompts]
        outs = rset.run()
        assert [outs[r] for r in rids] == baseline
        st = rset.spec_stats()
        assert st["proposed"] > 0 and "accept_rate" in st

    def test_evacuation_rebuilds_draft_state(self, mixed_prompts,
                                             baseline):
        """A replica failure mid-churn migrates requests whose n-gram
        state lives on the FAILED replica's proposer — the destination
        rebuilds it lazily from prompt+output and greedy outputs stay
        token-identical."""
        rset = serving.EngineReplicaSet(
            [_engine(spec_decode=True, draft_depth=4)
             for _ in range(2)]).warmup()
        rs.clear_faults()
        rs.install_faults("serve.replica@4")
        try:
            rids = []
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for p in mixed_prompts:
                    rids.append(rset.add_request(p, max_new_tokens=16))
                    rset.step()
                outs = rset.run()
        finally:
            rs.clear_faults()
        assert [outs[r] for r in rids] == baseline
        assert rset.failures == 1
        for rep in rset.replicas:
            assert rep.kv_blocks_used == 0


# ---------------------------------------------------------------------------
# telemetry + tooling plumbing
# ---------------------------------------------------------------------------

class TestSpecTelemetry:
    def test_counters_histogram_and_trace(self, tiny_model):
        sink = obs.InMemorySink()
        tel = obs.enable(sinks=[sink], crash_hooks=False)
        try:
            eng = _engine(tiny_model, spec_decode=True,
                          draft_depth=4).warmup()
            # fixed rng: this motif verifiably yields acceptance on the
            # tiny model (the counters below must all engage)
            rid = eng.add_request(
                _motif_prompt(5, 3, np.random.default_rng(42)),
                max_new_tokens=12)
            eng.run()
            snap = tel.registry.snapshot()
            assert snap["serve.spec.proposed"] > 0
            assert snap["serve.spec.accepted"] > 0
            assert "serve.spec.accept_len" in snap
            tracer = obs.get_request_tracer()
            tl = tracer.timeline(rid)
            retire = [e for e in tl["events"]
                      if e["phase"] == "retire"][0]
            assert retire["spec_accepted"] == \
                eng._states[rid].spec_accepted
            assert retire["spec_proposed"] > 0
        finally:
            obs.disable()

    def test_non_spec_trace_carries_no_spec_fields(self, tiny_model):
        tel = obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        try:
            eng = _engine(tiny_model).warmup()
            rid = eng.add_request(_prompt(5), max_new_tokens=4)
            eng.run()
            tl = obs.get_request_tracer().timeline(rid)
            retire = [e for e in tl["events"]
                      if e["phase"] == "retire"][0]
            assert "spec_accepted" not in retire
        finally:
            obs.disable()

    def test_report_folds_acceptance(self, tiny_model, tmp_path):
        jl = tmp_path / "t.jsonl"
        tel = obs.enable(sinks=[obs.JsonlSink(str(jl))],
                         crash_hooks=False)
        try:
            eng = _engine(tiny_model, spec_decode=True,
                          draft_depth=4).warmup()
            # fixed rng with verified acceptance (see test above)
            eng.add_request(_motif_prompt(5, 3, np.random.default_rng(13)),
                            max_new_tokens=12)
            eng.run()
        finally:
            obs.disable()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import telemetry_report
        events, _malformed = telemetry_report.load_events([str(jl)])
        agg = telemetry_report.summarize(events)
        md = telemetry_report.render(agg)
        assert "spec drafts proposed / accepted" in md
        # the serve_trace fold carries per-request acceptance
        assert any(t.get("spec_accepted") is not None
                   for t in agg["traces"])


class TestSpecBenchPlumbing:
    def test_bench_serve_spec_cpu(self):
        """The acceptance bar: on the repetitive workload the
        speculative engine emits MORE than one token per verify step
        (mean accepted tokens/step > 1.0) with outputs identical to
        the plain engine (asserted inside the bench)."""
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from decode_bench import bench_serve_spec
        r = bench_serve_spec(preset="tiny", max_batch=4, n_requests=6,
                             max_new=24, motif_len=6, motif_reps=3,
                             draft_depth=4, page_size=8)
        assert r["metric"] == "serve_spec_decode"
        assert r["tokens_per_verify_step"] > 1.0
        assert r["accept_rate"] > 0
        assert r["steps"] < r["base_steps"]
