"""Round-4 geometric sampling + incubate tail (graph ops, fused masked
softmax, identity_loss, ASP n:m sparsity).

Oracles: hand-computed reindex/sampling invariants; NumPy softmax.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu.geometric as G
import paddle_tpu.incubate as inc
import paddle_tpu.nn as nn


@pytest.fixture
def csc_graph():
    # 4 nodes; in-neighbors of v = row[colptr[v]:colptr[v+1]]
    colptr = np.array([0, 2, 4, 5, 7])
    row = np.array([1, 2, 0, 3, 0, 1, 2])
    return row, colptr


class TestReindex:
    def test_reindex_graph_ordering(self):
        x = np.array([0, 5, 9])
        neigh = np.array([5, 9, 7, 0, 7, 3])
        count = np.array([2, 2, 2])
        src, dst, nodes = G.reindex_graph(x, neigh, count)
        assert nodes.tolist() == [0, 5, 9, 7, 3]
        assert src.tolist() == [1, 2, 3, 0, 3, 4]
        assert dst.tolist() == [0, 0, 1, 1, 2, 2]

    def test_reindex_heter_shares_numbering(self):
        x = np.array([0, 5, 9])
        srcs, dsts, nodes = G.reindex_heter_graph(
            x, [np.array([5, 0]), np.array([9, 3])],
            [np.array([1, 1, 0]), np.array([0, 1, 1])])
        assert nodes.tolist()[:3] == [0, 5, 9]
        assert dsts[0].tolist() == [0, 1] and dsts[1].tolist() == [1, 2]
        # 3 appears only in type-1 neighbors → gets the next fresh id
        assert srcs[1].tolist() == [2, nodes.tolist().index(3)]


class TestSampling:
    def test_full_neighborhood(self, csc_graph):
        row, colptr = csc_graph
        # node 0 owns slots 0..1 (row 1,2); node 3 owns slots 5..6 (row 1,2)
        neigh, cnt = G.sample_neighbors(row, colptr, np.array([0, 3]),
                                        sample_size=-1)
        assert cnt.tolist() == [2, 2]
        assert sorted(neigh.tolist()[:2]) == [1, 2]
        assert sorted(neigh.tolist()[2:]) == [1, 2]

    def test_sample_size_respected(self, csc_graph):
        row, colptr = csc_graph
        neigh, cnt = G.sample_neighbors(row, colptr, np.array([3]),
                                        sample_size=2,
                                        rng=np.random.default_rng(0))
        assert cnt.tolist() == [2]
        assert len(set(neigh.tolist())) == 2  # without replacement

    def test_return_eids(self, csc_graph):
        row, colptr = csc_graph
        eids = np.arange(100, 107)
        neigh, cnt, out_eids = G.sample_neighbors(
            row, colptr, np.array([1]), sample_size=-1, eids=eids,
            return_eids=True)
        assert out_eids.tolist() == [102, 103]

    def test_weighted_prefers_heavy_edges(self, csc_graph):
        row, colptr = csc_graph
        # node 3 owns slots 5..6 (row 1, 2); weight slot 5 hugely
        w = np.array([1, 1, 1, 1, 1, 1000.0, 0.001])
        picks = []
        for s in range(30):
            neigh, _ = G.weighted_sample_neighbors(
                row, colptr, w, np.array([3]), sample_size=1,
                rng=np.random.default_rng(s))
            picks.append(neigh.tolist()[0])
        assert picks.count(1) >= 28  # row[5] == 1 carries ~all the weight

    def test_khop_sampler_shapes(self, csc_graph):
        row, colptr = csc_graph
        es, ed, sidx, rx = inc.graph_khop_sampler(
            row, colptr, np.array([0]), [2, 2],
            rng=np.random.default_rng(2))
        assert len(es) == len(ed)
        assert rx.tolist() == [0]
        # every edge endpoint is a valid local id
        assert max(es.tolist() + ed.tolist()) < len(sidx)

    def test_send_uv(self):
        m = G.send_uv(jnp.arange(4.0)[:, None], 2 * jnp.ones((4, 1)),
                      jnp.asarray([0, 2]), jnp.asarray([1, 3]), "mul")
        assert m.tolist() == [[0.0], [4.0]]


class TestIncubateOps:
    def test_softmax_mask_fuse_matches_numpy(self):
        x = np.random.RandomState(0).randn(2, 2, 4, 4).astype(np.float32)
        mask = np.zeros((2, 1, 4, 4), np.float32)
        mask[:, :, :, -1] = -1e9  # forbid last column
        got = np.asarray(inc.softmax_mask_fuse(jnp.asarray(x),
                                               jnp.asarray(mask)))
        z = x + mask
        e = np.exp(z - z.max(-1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                                   atol=1e-5)
        assert got[..., -1].max() < 1e-6

    def test_upper_triangle_is_causal(self):
        x = jnp.asarray(np.random.RandomState(1)
                        .randn(1, 1, 5, 5).astype(np.float32))
        p = np.asarray(inc.softmax_mask_fuse_upper_triangle(x))
        np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)
        assert np.abs(np.triu(p[0, 0], 1)).max() < 1e-6

    def test_identity_loss(self):
        v = jnp.asarray([1.0, 3.0])
        assert float(inc.identity_loss(v, "sum")) == 4.0
        assert float(inc.identity_loss(v, 1)) == 2.0
        np.testing.assert_allclose(np.asarray(inc.identity_loss(v, "none")),
                                   [1.0, 3.0])


class TestASP:
    def test_create_mask_keeps_top2_of_4(self):
        t = np.array([[0.1, -0.9, 0.5, 0.2], [4.0, 0.0, -3.0, 1.0]],
                     np.float32)
        m = np.asarray(inc.asp.create_mask(t))
        np.testing.assert_array_equal(m, [[0, 1, 1, 0], [1, 0, 1, 0]])

    def test_prune_model_halves_density(self):
        lin = nn.Linear(8, 8)
        masks = inc.asp.prune_model(lin)
        assert "weight" in masks and "bias" not in masks
        assert inc.asp.check_sparsity(lin.weight, n=2, m=4)
        assert abs(inc.asp.calculate_density(lin.weight) - 0.5) < 1e-6

    def test_excluded_layers(self):
        lin = nn.Linear(4, 4)
        inc.asp.set_excluded_layers(["weight"])
        try:
            masks = inc.asp.prune_model(lin)
            assert masks == {}
        finally:
            inc.asp.reset_excluded_layers()

    def test_check_sparsity_rejects_dense(self):
        assert not inc.asp.check_sparsity(np.ones((4, 4)), n=2, m=4)
