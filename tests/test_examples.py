"""Smoke tests: the examples/ scripts must run end-to-end on the CPU mesh
(tiny configs). Mirrors the reference's runnable-demo guarantee."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *argv):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *argv],
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "done" in p.stdout
    return p.stdout


def test_train_llama_tiny():
    out = _run("train_llama.py", "--steps", "6", "--seq", "64", "--batch", "2")
    assert "loss=" in out


def test_train_llama_hybrid():
    out = _run("train_llama.py", "--steps", "4", "--seq", "64", "--batch",
               "4", "--dp", "2", "--mp", "2", "--sharding", "2")
    assert "loss=" in out


def test_train_moe_ep():
    out = _run("train_moe.py", "--steps", "4", "--seq", "32", "--ep", "2")
    assert "loss=" in out


def test_train_ps_ctr():
    out = _run("train_ps_ctr.py", "--steps", "30")
    assert "loss=" in out


def test_train_long_context_ring():
    out = _run("train_long_context.py", "--steps", "4", "--seq", "128",
               "--sep", "4", "--dp", "2")
    assert "loss=" in out and "sep=4" in out


def test_train_long_context_ulysses():
    out = _run("train_long_context.py", "--steps", "4", "--seq", "128",
               "--sep", "2", "--dp", "2", "--impl", "ulysses")
    assert "loss=" in out


@pytest.mark.parametrize("argv", [
    ("--algo", "weight_only_int8"),
    ("--algo", "weight_only_int4", "--mp", "2"),
])
def test_serve_quantized(argv):
    _run("serve_quantized.py", *argv)
