"""pdtpu-lint — the framework-invariant static analyzer
(paddle_tpu/analysis, docs/ANALYSIS.md).

Each of the six rules is proven on small fixture snippets: a true
positive, a true negative, a suppressed positive, and (shared) a
baselined positive; plus the whole-tree smoke test the ``lint`` CI
gate stands on, the SITES-extraction parity check against the real
``resilience.SITES``, and the jax-free CLI contract.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from pdtpu_lint import load_analysis  # noqa: E402

analysis = load_analysis()

FAULTS_FIXTURE = '''
SITES = ("step", "ckpt.save", "serve.swap")
_EXC_NAMES = {"InjectedFault": None, "OSError": None}
'''

DOC_FIXTURE = """
### Sites

| site | fires in |
|---|---|
| `step` | the train step |
| `ckpt.save` | checkpoint writes |
| `serve.swap` | swap I/O |
"""


def run_lint(tmp_path, files, baseline=None, rules=None,
             with_registry=True):
    """Write ``files`` (rel → source) under a scratch repo root and
    analyze them."""
    paths = []
    for rel, content in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(content))
        paths.append(rel)
    if with_registry:
        f = tmp_path / "paddle_tpu" / "resilience" / "faults.py"
        if not f.exists():
            f.parent.mkdir(parents=True, exist_ok=True)
            f.write_text(FAULTS_FIXTURE)
        d = tmp_path / "docs" / "RESILIENCE.md"
        if not d.exists():
            d.parent.mkdir(parents=True, exist_ok=True)
            d.write_text(DOC_FIXTURE)
    return analysis.analyze(str(tmp_path), paths=paths,
                            baseline=baseline, rules=rules)


def rules_of(res):
    return [f.rule for f in res.findings]


# ---------------------------------------------------------------------------
# rule 1: donation-safety
# ---------------------------------------------------------------------------

class TestDonationSafety:
    def test_positive_read_after_dispatch(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(state, batch):
                step = jax.jit(run, donate_argnums=(0,))
                new_state = step(state, batch)
                return state["loss"]     # read-after-free
        """})
        assert rules_of(res) == ["donation-safety"]
        assert "'state'" in res.findings[0].message

    def test_positive_view_alias(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax
            import numpy as np

            def go(params, batch):
                snap = np.asarray(params)    # zero-copy view
                step = jax.jit(run, donate_argnums=(0,))
                params = step(params, batch)
                return snap.sum()            # view of the dead buffer
        """})
        assert rules_of(res) == ["donation-safety"]
        assert "view" in res.findings[0].message

    def test_negative_rebind_and_branches(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(state, batch, mesh):
                step = jax.jit(run, donate_argnums=(0,))
                state = step(state, batch)       # x = f(x) rebind
                ok = state["loss"]
                if mesh is not None:
                    with mesh:
                        return step(state, batch)
                return step(state, batch)        # sibling, not "after"
        """})
        assert rules_of(res) == []

    def test_negative_self_attr_lifecycle(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            class Eng:
                def build(self):
                    self._fn = jax.jit(run, donate_argnums=(1,))

                def step(self, tok):
                    out, caches = self._fn(tok, self.kv.caches)
                    self.kv.caches = caches
                    return out
        """})
        assert rules_of(res) == []

    def test_positive_cross_method_self_attr(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            class Eng:
                def build(self):
                    self._fn = jax.jit(run, donate_argnums=(1,))

                def step(self, tok):
                    out, caches = self._fn(tok, self.kv.caches)
                    stale = self.kv.caches[0]    # donated, not rebound
                    self.kv.caches = caches
                    return out, stale
        """})
        assert rules_of(res) == ["donation-safety"]

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(state, batch):
                step = jax.jit(run, donate_argnums=(0,))
                new_state = step(state, batch)
                # pdtpu-lint: disable=donation-safety — fixture
                return state["loss"]
        """})
        assert rules_of(res) == []
        assert [f.rule for f in res.suppressed] == ["donation-safety"]
        assert res.stale_suppressions == []


# ---------------------------------------------------------------------------
# rule 2: compat-symbol
# ---------------------------------------------------------------------------

class TestCompatSymbol:
    def test_positives(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from jax.experimental.shard_map import shard_map
            from jax.experimental.pallas import tpu as pltpu

            def f(mesh):
                params = pltpu.TPUCompilerParams()
                g = getattr(pltpu, "CompilerParams")
                return shard_map(f, mesh=mesh, in_specs=(), out_specs=(),
                                 check_rep=False)
        """})
        assert rules_of(res) == ["compat-symbol"] * 4

    def test_negative_via_compat(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu.core.compat import (pallas_compiler_params,
                                                shard_map)

            def f(mesh):
                p = pallas_compiler_params()
                return shard_map(f, mesh=mesh, in_specs=(), out_specs=(),
                                 check_vma=False)
        """})
        assert rules_of(res) == []

    def test_compat_module_exempt(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/core/compat.py": """
            from jax.experimental.shard_map import shard_map as _old
        """})
        assert rules_of(res) == []

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            # pdtpu-lint: disable=compat-symbol — fixture
            from jax.experimental.shard_map import shard_map
        """})
        assert rules_of(res) == []
        assert len(res.suppressed) == 1


# ---------------------------------------------------------------------------
# rule 3: unguarded-telemetry
# ---------------------------------------------------------------------------

class TestUnguardedTelemetry:
    def test_positive_registry(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu import observability as obs

            def hot():
                reg = obs.get_registry()
                reg.counter("serve.steps").inc()    # None when disabled
        """})
        assert rules_of(res) == ["unguarded-telemetry"]

    def test_positive_hook_container(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu.resilience import _state as _rs_state

            def hot():
                fi = _rs_state.FAULTS[0]
                fi("step")                          # unguarded fire
        """})
        assert rules_of(res) == ["unguarded-telemetry"]

    def test_positive_chained_getter(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu import observability as obs

            def hot():
                obs.get_telemetry().emit({"event": "x"})
        """})
        assert rules_of(res) == ["unguarded-telemetry"]

    def test_negative_guard_idioms(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu import observability as obs
            from paddle_tpu.observability import _state as _obs_state
            from paddle_tpu.resilience import _state as _rs_state

            def a():
                reg = obs.get_registry()
                if reg is not None:
                    reg.counter("x").inc()

            def b():
                reg = obs.get_registry()
                if reg is None:
                    return
                reg.gauge("y").set(1)

            def c():
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    fi("step")
                mon = _obs_state.MONITOR[0]
                steps = mon.total_steps if mon is not None else None
                obs.emit_event("done", steps=steps)   # sanctioned wrapper
                if _obs_state.EMIT[0] is not None:
                    _obs_state.EMIT[0]({"event": "z"})

            def d(plan):
                reg = obs.get_registry()
                if reg is not None and plan:
                    reg.counter("x").inc()
                e = _obs_state.EMIT[0]
                ok = e is not None and e({"event": "w"})
        """})
        assert rules_of(res) == []

    def test_exempt_inside_packages(self, tmp_path):
        res = run_lint(tmp_path, {
            "paddle_tpu/observability/thing.py": """
                def hot(reg):
                    reg = get_registry()
                    reg.counter("x").inc()
            """})
        assert rules_of(res) == []

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu import observability as obs

            def cold():
                reg = obs.get_registry()
                # pdtpu-lint: disable=unguarded-telemetry — cold path
                reg.counter("x").inc()
        """})
        assert rules_of(res) == []
        assert len(res.suppressed) == 1


# ---------------------------------------------------------------------------
# rule 4: retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazard:
    def test_positive_host_scalar(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(x, t):
                f = jax.jit(run)
                return f(x.item(), float(t))
        """})
        assert rules_of(res) == ["retrace-hazard"] * 2

    def test_positive_jit_in_loop(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(fns, x):
                for fn in fns:
                    out = jax.jit(fn)(x)
        """})
        assert rules_of(res) == ["retrace-hazard"]

    def test_positive_unhashable_static(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(x):
                f = jax.jit(run, static_argnums=(1,))
                return f(x, [1, 2, 3])
        """})
        assert rules_of(res) == ["retrace-hazard"]
        assert "unhashable" in res.findings[0].message

    def test_positive_mutable_global(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            _CFG = {"scale": 2.0}

            @jax.jit
            def scaled(x):
                return x * _CFG["scale"]
        """})
        assert rules_of(res) == ["retrace-hazard"]
        assert "_CFG" in res.findings[0].message

    def test_static_argnames_resolved_to_positions(self, tmp_path):
        """static_argnames map to positions via the wrapped signature:
        a host scalar at a name-static position is NOT flagged, and an
        unhashable literal there IS (review finding)."""
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def run(x, mode):
                return x

            def go(x, m):
                f = jax.jit(run, static_argnames=("mode",))
                ok = f(x, int(m))            # static position: fine
                bad = f(x, [1, 2])           # unhashable static
                return ok, bad
        """})
        assert rules_of(res) == ["retrace-hazard"]
        assert "unhashable" in res.findings[0].message

    def test_static_argnames_unresolvable_stays_silent(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def go(fn, x, m):
                f = jax.jit(fn, static_argnames=("mode",))
                return f(x, float(m))        # can't map: no finding
        """})
        assert rules_of(res) == []

    def test_negatives(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import functools
            import jax
            import jax.numpy as jnp

            _CFG = {"scale": 2.0}

            @functools.partial(jax.jit, static_argnums=(1,))
            def powed(x, n):
                return x ** n

            def go(fn, x, arr):
                memo = None
                for _ in range(3):
                    if memo is None:
                        memo = make(fn)       # jit made elsewhere
                f = jax.jit(fn)
                y = f(jnp.asarray(arr))       # device value: fine
                z = powed(y, 2)               # hashable static: fine
                s = float(_CFG["scale"])      # outside jit: fine
                return y, z, s
        """})
        assert rules_of(res) == []

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def bench(fns, x):
                for fn in fns:
                    # pdtpu-lint: disable=retrace-hazard — deliberate
                    out = jax.jit(fn)(x)
        """})
        assert rules_of(res) == []
        assert len(res.suppressed) == 1

    def test_positive_per_step_tuned_config_read(self, tmp_path):
        """R4e: a tuned-config lookup inside the dispatch loop is a
        per-step read of trace-time-frozen state — flagged."""
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax
            from paddle_tpu.ops.tuning import tuned_config

            def serve_loop(step, x):
                while True:
                    cfg = tuned_config("serving", "h64_l2")
                    x = step(x, cfg["prefill_chunk"])
        """})
        assert rules_of(res) == ["retrace-hazard"]
        assert "tuned_config" in res.findings[0].message

    def test_positive_tuned_config_attr_call_in_loop(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu.ops import tuning

            def pump(engines):
                for e in engines:
                    e.chunk = tuning.tuned_config("serving")["c"]
        """})
        assert rules_of(res) == ["retrace-hazard"]

    def test_negative_tuned_config_trace_time(self, tmp_path):
        """The sanctioned idiom: tuned-config lookups at construction
        time or inside a jit-traced function (resolved once, baked into
        the compiled program) stay silent."""
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax
            from paddle_tpu.ops.tuning import tuned_config

            class Engine:
                def __init__(self):
                    # construction time: resolved before warmup
                    self.page = tuned_config("serving").get("page", 16)

            @jax.jit
            def kernel_wrapper(x):
                # trace time: runs once per compile, frozen after
                cfg = tuned_config("fused_swiglu_mlp", "h64_i128")
                return x * cfg.get("block_t", 256)

            def run(fns, x):
                cfg = tuned_config("serving")   # hoisted: fine
                for fn in fns:
                    x = fn(x, cfg)
                return x
        """})
        assert rules_of(res) == []

    def test_positive_draft_len_scalar(self, tmp_path):
        """R4f: the speculative draft length fed to the compiled step
        as a fresh Python int per step — directly as len(draft) and as
        a draft-named local bound to len(...) — flagged."""
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax

            def serve(step, x, drafts):
                f = jax.jit(step)
                for d in drafts:
                    out = f(x, len(d.draft))
                    draft_len = len(d.draft)
                    out = f(x, draft_len)
        """})
        assert rules_of(res) == ["retrace-hazard"] * 2
        assert all("draft" in f.message for f in res.findings)

    def test_negative_draft_len_as_data_or_static(self, tmp_path):
        """The sanctioned paths: draft length riding the traced span
        arrays (jnp.asarray of numpy), and a construction-fixed depth
        at a warmup-compiled STATIC position — both silent."""
        res = run_lint(tmp_path, {"pkg/a.py": """
            import jax
            import numpy as np
            import jax.numpy as jnp

            def serve(step, x, plan, depth):
                f = jax.jit(step, static_argnums=(2,))
                lens = np.zeros((8,), np.int32)
                for i, st in plan:
                    lens[i] = 1 + len(st.draft)
                draft_depth = int(depth)      # construction-time once
                for _ in range(4):
                    out = f(x, jnp.asarray(lens), draft_depth)
        """})
        assert rules_of(res) == []

class TestFaultSite:
    def test_positive_unregistered_fire(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu.resilience import _state as _rs_state

            def hot():
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    fi("serve.swpa")        # typo'd site
        """})
        assert rules_of(res) == ["fault-site"]

    def test_positive_bad_spec_and_kwarg(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            import os
            from paddle_tpu import resilience as rs

            def go(pol, fn):
                rs.install_faults("nosuch@1")
                rs.install_faults("step@@")
                os.environ["PDTPU_FAULTS"] = "step@1:NoSuchError"
                pol.run(fn, site="serve.swpa")
        """})
        assert sorted(rules_of(res)) == ["fault-site"] * 4

    def test_negative(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu import resilience as rs
            from paddle_tpu.resilience import _state as _rs_state

            def go(pol, fn, is_save):
                rs.install_faults("step@3x2:OSError,serve.swap@0")
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    fi("ckpt.save" if is_save else "step")
                pol.run(fn, site="supervisor")   # retry label, not a site
                pol.run(fn, site="serve.swap")
        """})
        assert rules_of(res) == []

    def test_docs_drift_both_directions(self, tmp_path):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "RESILIENCE.md").write_text(
            "| site | fires in |\n|---|---|\n"
            "| `step` | x |\n| `ckpt.save` | y |\n| `ghost.site` | z |\n")
        res = run_lint(tmp_path, {"pkg/a.py": "x = 1\n"},
                       with_registry=True)
        msgs = " ".join(f.message for f in res.findings)
        assert rules_of(res) == ["fault-site"] * 2
        assert "ghost.site" in msgs          # doc lists unregistered
        assert "serve.swap" in msgs          # registered missing in doc

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            from paddle_tpu.resilience import _state as _rs_state

            def hot():
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    # pdtpu-lint: disable=fault-site — fixture
                    fi("serve.swpa")
        """})
        assert rules_of(res) == []
        assert len(res.suppressed) == 1

    def test_registry_extraction_matches_runtime(self):
        """The AST-extracted registry IS resilience.SITES/_EXC_NAMES."""
        with open(os.path.join(REPO, "paddle_tpu", "resilience",
                               "faults.py")) as f:
            sites, excs = analysis.ALL_RULES[
                "fault-site"].extract_registry(f.read())
        from paddle_tpu.resilience import faults
        assert sites == faults.SITES
        assert set(excs) == set(faults._EXC_NAMES)


# ---------------------------------------------------------------------------
# rule 6: lock-discipline
# ---------------------------------------------------------------------------

_LOCK_HEADER = """
    import threading

    class Srv:
        def __init__(self):
            self._lock = threading.Lock()
            self._routes: dict = {}     # guarded_by: _lock
"""


class TestLockDiscipline:
    def test_positive_unlocked_access(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": _LOCK_HEADER + """
        def loop(self):
            q = self._routes.get("x")   # no lock held
    """})
        assert rules_of(res) == ["lock-discipline"]

    def test_negative_with_lock_requires_and_init(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": _LOCK_HEADER + """
        def loop(self):
            with self._lock:
                q = self._routes.get("x")

        # requires-lock: _lock
        def pump(srv):
            return len(srv._routes)
    """})
        assert rules_of(res) == []

    def test_cross_module_access_checked(self, tmp_path):
        res = run_lint(tmp_path, {
            "pkg/a.py": _LOCK_HEADER,
            "pkg/b.py": """
                def peek(srv):
                    return srv._routes   # other module, still checked
            """})
        assert rules_of(res) == ["lock-discipline"]
        assert res.findings[0].path == "pkg/b.py"

    def test_suppressed(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": _LOCK_HEADER + """
        def bench(self):
            # pdtpu-lint: disable=lock-discipline — single-threaded
            return self._routes
    """})
        assert rules_of(res) == []
        assert len(res.suppressed) == 1


# ---------------------------------------------------------------------------
# baseline / stale handling
# ---------------------------------------------------------------------------

class TestBaseline:
    SRC = {"pkg/a.py": """
        from paddle_tpu import observability as obs

        def hot():
            reg = obs.get_registry()
            reg.counter("x").inc()
    """}

    def test_baselined_finding_passes(self, tmp_path):
        first = run_lint(tmp_path, self.SRC)
        assert not first.ok
        baseline = [f.to_baseline_entry() for f in first.findings]
        second = run_lint(tmp_path, self.SRC, baseline=baseline)
        assert second.ok
        assert [f.rule for f in second.baselined] == ["unguarded-telemetry"]
        assert second.stale_baseline == []

    def test_baseline_survives_line_drift(self, tmp_path):
        first = run_lint(tmp_path, self.SRC)
        baseline = [dict(f.to_baseline_entry(), line=999)
                    for f in first.findings]
        second = run_lint(tmp_path, self.SRC, baseline=baseline)
        assert second.ok and len(second.baselined) == 1

    def test_stale_baseline_warns(self, tmp_path):
        baseline = [{"rule": "unguarded-telemetry", "file": "pkg/a.py",
                     "line": 1, "code": "gone_line()"}]
        res = run_lint(tmp_path, self.SRC, baseline=baseline)
        assert not res.ok                   # the live finding is NOT eaten
        assert len(res.stale_baseline) == 1

    def test_stale_suppression_warns(self, tmp_path):
        res = run_lint(tmp_path, {"pkg/a.py": """
            def fine():
                # pdtpu-lint: disable=donation-safety — obsolete
                return 1
        """})
        assert res.ok
        assert len(res.stale_suppressions) == 1

    def test_trailing_suppression_does_not_leak_to_next_statement(
            self, tmp_path):
        """A trailing disable on statement N must not also suppress
        statement N+1 (review finding: the 'line above' form only
        counts on comment-only lines)."""
        res = run_lint(tmp_path, {"pkg/a.py": _LOCK_HEADER + """
        def loop(self):
            a = self._routes.get("x")   # pdtpu-lint: disable=lock-discipline
            b = self._routes.get("y")
    """})
        assert rules_of(res) == ["lock-discipline"]
        assert res.findings[0].line == res.suppressed[0].line + 1

    def test_rule_subset_does_not_report_live_suppressions_stale(
            self, tmp_path):
        """Under --rules subsets the un-run rules' suppressions were
        never evaluated — 'remove the comment' advice would break the
        next full run (review finding)."""
        files = {"pkg/a.py": """
            import jax

            def bench(fns, x):
                for fn in fns:
                    # pdtpu-lint: disable=retrace-hazard — deliberate
                    out = jax.jit(fn)(x)
        """}
        res = run_lint(tmp_path, files, rules=["compat-symbol"])
        assert res.ok and res.stale_suppressions == []
        res = run_lint(tmp_path, files)      # full run: evaluated, used
        assert res.ok and res.stale_suppressions == []


# ---------------------------------------------------------------------------
# whole tree + CLI
# ---------------------------------------------------------------------------

class TestWholeTree:
    def test_full_tree_clean_and_fast(self):
        """The standing scan set has zero non-baselined findings (the
        lint gate's contract) and completes well inside the 30 s
        budget."""
        t0 = time.perf_counter()
        baseline = analysis.load_baseline(
            os.path.join(REPO, "tools", "lint_baseline.json"))
        res = analysis.analyze(REPO, baseline=baseline)
        dt = time.perf_counter() - t0
        assert res.errors == []
        assert res.findings == [], "\n".join(
            f"{f.location()}: {f.rule}: {f.message}" for f in res.findings)
        assert res.files_scanned > 100
        assert dt < 30.0, f"analyzer took {dt:.1f}s (budget 30s)"

    def test_live_suppressions_not_stale(self):
        """Every inline disable in the tree still suppresses a real
        finding — the satellite-6 only-shrinks contract."""
        res = analysis.analyze(REPO)
        assert res.stale_suppressions == []
        assert len(res.suppressed) >= 1     # decode_bench keeps some

    def test_cli_runs_jax_free(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "pdtpu_lint.py")],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "(jax imported: False)" in r.stdout

    def test_cli_json_reports_and_enforces_jax_free(self):
        """--json carries the jax_imported flag and keeps the same
        hard-fail contract as text mode (review finding)."""
        import json as _json
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "pdtpu_lint.py"),
             "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        payload = _json.loads(r.stdout)
        assert payload["jax_imported"] is False
        assert payload["findings"] == []

    def test_cli_scoped_update_baseline_refused(self):
        """--update-baseline under explicit paths/--rules would rewrite
        the baseline from a partial scan, silently deleting entries for
        everything unscanned (review finding) — it must refuse."""
        for extra in (["paddle_tpu/serving"], ["--rules", "compat-symbol"]):
            r = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "pdtpu_lint.py"),
                 "--update-baseline", "--no-baseline"] + extra,
                cwd=REPO, capture_output=True, text=True, timeout=120)
            assert r.returncode == 2, (extra, r.stdout, r.stderr)
            assert "full scan" in r.stderr

    def test_cli_rule_subset_and_unknown(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "pdtpu_lint.py"),
             "--rules", "compat-symbol", "paddle_tpu/serving"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "pdtpu_lint.py"),
             "--rules", "nope"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 2

    def test_package_importable_under_paddle_tpu(self):
        """``import paddle_tpu.analysis`` (the package spelling) exposes
        the same surface the CLI loader does."""
        import paddle_tpu.analysis as pa
        assert set(pa.ALL_RULES) == set(analysis.ALL_RULES)
