"""Regression tests for issues found in code review."""

import copy

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn import functional as F


def test_sequential_named_tuples():
    s = nn.Sequential(("fc", nn.Linear(4, 4)), ("act", nn.ReLU()))
    assert list(s._sub_layers) == ["fc", "act"]
    y = s(jnp.ones((1, 4)))
    assert not np.allclose(np.asarray(y), 1.0)  # not identity


def test_transformer_encoder_prototype_layer():
    proto = nn.TransformerEncoderLayer(8, 2, 16, dropout=0.0)
    enc = nn.TransformerEncoder(proto, 3)
    assert len(enc.layers) == 3
    # parameters are independent copies, not shared
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1
    out = enc.eval()(jnp.ones((1, 4, 8)))
    assert out.shape == (1, 4, 8)


def test_cross_entropy_class_weight():
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.asarray([0, 1])
    w = jnp.asarray([1.0, 3.0])
    loss = F.cross_entropy(logits, labels, weight=w)
    logp = np.log(np.exp([2.0, 2.0]) / (np.exp(2.0) + np.exp(0.0)))
    expect = (1.0 * -logp[0] + 3.0 * -logp[1]) / 4.0  # weighted mean
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)


def test_scaler_skips_optimizer_on_inf():
    class One(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 1, bias_attr=False)

        def forward(self, x):
            return self.fc(x)

    def loss_fn(model, batch):
        return (model(batch["x"]) * batch["scale"]).mean()

    model = One()
    opt = optimizer.AdamW(learning_rate=0.1, parameters=model.parameters())
    scaler = amp.GradScaler(init_loss_scaling=4.0)
    step = TrainStep(model, loss_fn, opt, scaler=scaler)
    state = step.init_state(0)
    w0 = np.asarray(state["params"]["fc.weight"]).copy()
    m0 = np.asarray(state["opt"]["moment1"]["fc.weight"]).copy()
    bad = {"x": jnp.ones((2, 2)), "scale": jnp.asarray(jnp.inf)}
    state, m = step(state, bad)
    # overflow: params AND optimizer moments unchanged, scale halved
    np.testing.assert_allclose(np.asarray(state["params"]["fc.weight"]), w0)
    np.testing.assert_allclose(np.asarray(state["opt"]["moment1"]["fc.weight"]), m0)
    assert float(state["scaler"]["scale"]) == 2.0
    good = {"x": jnp.ones((2, 2)), "scale": jnp.asarray(1.0)}
    state, m = step(state, good)
    assert not np.allclose(np.asarray(state["params"]["fc.weight"]), w0)


def test_expand_trailing_align():
    x = jnp.ones((3,))
    assert pt.expand(x, [2, -1]).shape == (2, 3)
    y = jnp.ones((4, 3))
    assert pt.expand(y, [2, -1, -1]).shape == (2, 4, 3)


def test_multinomial_without_replacement():
    probs = jnp.ones((16,)) / 16.0
    idx = np.asarray(pt.multinomial(probs, num_samples=8, replacement=False))
    assert len(set(idx.tolist())) == 8  # all unique


def test_rope_non_neox_style(rng):
    q = jnp.asarray(rng.standard_normal((1, 5, 2, 8)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 5, 2, 8)).astype(np.float32))
    qn, kn, _ = F.fused_rotary_position_embedding(q, k, use_neox_rotary_style=False)
    # norm preserved, position 0 unchanged, differs from neox style
    np.testing.assert_allclose(np.linalg.norm(np.asarray(qn), axis=-1),
                               np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(qn)[:, 0], np.asarray(q)[:, 0],
                               rtol=1e-5, atol=1e-6)
    qx, _, _ = F.fused_rotary_position_embedding(q, k, use_neox_rotary_style=True)
    assert not np.allclose(np.asarray(qn)[:, 1:], np.asarray(qx)[:, 1:])


def test_ops_star_export_clean():
    assert not hasattr(pt, "jnp")
    import paddle_tpu.ops as ops
    assert "jnp" not in ops.__all__ and "jax" not in ops.__all__
    assert "matmul" in ops.__all__ and "concat" in ops.__all__


# -- round-4 advisor findings (ADVICE.md round 3) ---------------------------

def test_fused_multi_transformer_int8_cache_is_quantized(rng):
    """init_cache(dtype='int8') must yield quantized 4-tuples, never raw
    unscaled int8 2-tuples, and decode through them must stay close to
    the f32-cache rollout (advisor medium, incubate/nn/__init__.py)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    m = FusedMultiTransformer(32, 4, 64, num_layers=2)
    caches = m.init_cache(2, 16, dtype="int8")
    assert len(caches) == 2 and len(caches[0]) == 4
    assert caches[0][0].dtype == jnp.int8
    assert caches[0][2].dtype == jnp.float32  # scales
    x = jnp.asarray(rng.standard_normal((2, 5, 32)).astype(np.float32))
    ref_caches = m.init_cache(2, 16, dtype=jnp.float32)
    out_i8, c_i8 = m(x, caches=caches)
    out_fp, c_fp = m(x, caches=ref_caches)
    np.testing.assert_allclose(np.asarray(out_i8), np.asarray(out_fp),
                               rtol=0.1, atol=0.05)
    # one decode step through the quantized cache
    tok = jnp.asarray(rng.standard_normal((2, 1, 32)).astype(np.float32))
    lens = jnp.array([5, 5], jnp.int32)
    d_i8, _ = m(tok, caches=c_i8, seq_lens=lens)
    d_fp, _ = m(tok, caches=c_fp, seq_lens=lens)
    np.testing.assert_allclose(np.asarray(d_i8), np.asarray(d_fp),
                               rtol=0.15, atol=0.08)


def test_fill_diagonal_wrap_tall():
    t = np.zeros((7, 3), np.float32)
    expect = t.copy()
    # torch/paddle wrap semantics: diagonal restarts every (cols+1) rows
    for r in range(7):
        if r % 4 < 3:
            expect[r, r % 4] = 5.0
    got = np.asarray(pt.fill_diagonal_(jnp.asarray(t), 5.0, wrap=True))
    np.testing.assert_array_equal(got, expect)


def test_uniform_seed_reproducible():
    x = jnp.zeros((64,))
    a = np.asarray(pt.uniform_(x, seed=1234))
    b = np.asarray(pt.uniform_(x, seed=1234))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(pt.uniform_(x, seed=0))
    d = np.asarray(pt.uniform_(x, seed=0))
    assert not np.array_equal(c, d)  # seed=0 draws from the global stream
    n1 = np.asarray(pt.normal_(x, seed=7))
    n2 = np.asarray(pt.normal_(x, seed=7))
    np.testing.assert_array_equal(n1, n2)


def test_default_convert_namedtuple():
    import collections
    from paddle_tpu.io import default_convert_fn
    Pair = collections.namedtuple("Pair", ["a", "b"])
    out = default_convert_fn(Pair(np.ones((2,)), 3))
    assert isinstance(out, Pair)
    assert isinstance(out.a, jax.Array) and isinstance(out.b, jax.Array)


def test_matrix_nms_prefilters_low_scores():
    """Low-score boxes must not join the top_k set and decay others
    (advisor low, vision/ops_tail3.py)."""
    from paddle_tpu.vision.ops_tail3 import matrix_nms
    boxes = jnp.asarray([[0, 0, 10, 10], [0, 0, 10, 10], [20, 20, 30, 30]],
                        jnp.float32)
    # box 1 overlaps box 0 perfectly but is below score_threshold: with
    # pre-filtering, box 0 keeps score 0.9 un-decayed by box 1
    scores = jnp.asarray([[0.9, 0.05, 0.8]], jnp.float32)
    out, _ = matrix_nms(boxes, scores, score_threshold=0.1, nms_top_k=3,
                        keep_top_k=3)
    out = np.asarray(out)
    kept = out[out[:, 1] > 0]
    np.testing.assert_allclose(kept[:, 1].max(), 0.9, rtol=1e-5)
    assert (np.abs(kept[:, 1] - 0.05) > 1e-3).all()  # filtered box gone


def test_var_dispatch_fast_path_flag():
    from paddle_tpu import static
    assert static.Var._any_created[0] in (True, False)
    # building a program flips the flag; dispatch still records nodes
    prog = static.Program()
    x = prog.data("x", (2, 2))
    assert static.Var._any_created[0] is True
    y = pt.ops.exp(x) if hasattr(pt.ops.exp, "_var_dispatch") else x
    assert isinstance(y, static.Var)


def test_default_collate_namedtuple_and_jit_fill_diagonal():
    import collections
    from paddle_tpu.io import default_collate_fn
    Pair = collections.namedtuple("Pair", ["a", "b"])
    out = default_collate_fn([Pair(np.ones((2,)), 1), Pair(np.zeros((2,)), 2)])
    assert isinstance(out, Pair) and out.a.shape == (2, 2)
    # wrap branch must survive jit (indices computed statically)
    got = jax.jit(lambda x: pt.fill_diagonal_(x, 5.0, wrap=True))(
        jnp.zeros((7, 3)))
    assert float(got.sum()) == 30.0
    import pytest
    with pytest.raises(NotImplementedError):
        pt.fill_diagonal_(jnp.zeros((7, 3)), 1.0, offset=1, wrap=True)
