"""Regression tests for issues found in code review."""

import copy

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn import functional as F


def test_sequential_named_tuples():
    s = nn.Sequential(("fc", nn.Linear(4, 4)), ("act", nn.ReLU()))
    assert list(s._sub_layers) == ["fc", "act"]
    y = s(jnp.ones((1, 4)))
    assert not np.allclose(np.asarray(y), 1.0)  # not identity


def test_transformer_encoder_prototype_layer():
    proto = nn.TransformerEncoderLayer(8, 2, 16, dropout=0.0)
    enc = nn.TransformerEncoder(proto, 3)
    assert len(enc.layers) == 3
    # parameters are independent copies, not shared
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1
    out = enc.eval()(jnp.ones((1, 4, 8)))
    assert out.shape == (1, 4, 8)


def test_cross_entropy_class_weight():
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.asarray([0, 1])
    w = jnp.asarray([1.0, 3.0])
    loss = F.cross_entropy(logits, labels, weight=w)
    logp = np.log(np.exp([2.0, 2.0]) / (np.exp(2.0) + np.exp(0.0)))
    expect = (1.0 * -logp[0] + 3.0 * -logp[1]) / 4.0  # weighted mean
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)


def test_scaler_skips_optimizer_on_inf():
    class One(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 1, bias_attr=False)

        def forward(self, x):
            return self.fc(x)

    def loss_fn(model, batch):
        return (model(batch["x"]) * batch["scale"]).mean()

    model = One()
    opt = optimizer.AdamW(learning_rate=0.1, parameters=model.parameters())
    scaler = amp.GradScaler(init_loss_scaling=4.0)
    step = TrainStep(model, loss_fn, opt, scaler=scaler)
    state = step.init_state(0)
    w0 = np.asarray(state["params"]["fc.weight"]).copy()
    m0 = np.asarray(state["opt"]["moment1"]["fc.weight"]).copy()
    bad = {"x": jnp.ones((2, 2)), "scale": jnp.asarray(jnp.inf)}
    state, m = step(state, bad)
    # overflow: params AND optimizer moments unchanged, scale halved
    np.testing.assert_allclose(np.asarray(state["params"]["fc.weight"]), w0)
    np.testing.assert_allclose(np.asarray(state["opt"]["moment1"]["fc.weight"]), m0)
    assert float(state["scaler"]["scale"]) == 2.0
    good = {"x": jnp.ones((2, 2)), "scale": jnp.asarray(1.0)}
    state, m = step(state, good)
    assert not np.allclose(np.asarray(state["params"]["fc.weight"]), w0)


def test_expand_trailing_align():
    x = jnp.ones((3,))
    assert pt.expand(x, [2, -1]).shape == (2, 3)
    y = jnp.ones((4, 3))
    assert pt.expand(y, [2, -1, -1]).shape == (2, 4, 3)


def test_multinomial_without_replacement():
    probs = jnp.ones((16,)) / 16.0
    idx = np.asarray(pt.multinomial(probs, num_samples=8, replacement=False))
    assert len(set(idx.tolist())) == 8  # all unique


def test_rope_non_neox_style(rng):
    q = jnp.asarray(rng.standard_normal((1, 5, 2, 8)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 5, 2, 8)).astype(np.float32))
    qn, kn, _ = F.fused_rotary_position_embedding(q, k, use_neox_rotary_style=False)
    # norm preserved, position 0 unchanged, differs from neox style
    np.testing.assert_allclose(np.linalg.norm(np.asarray(qn), axis=-1),
                               np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(qn)[:, 0], np.asarray(q)[:, 0],
                               rtol=1e-5, atol=1e-6)
    qx, _, _ = F.fused_rotary_position_embedding(q, k, use_neox_rotary_style=True)
    assert not np.allclose(np.asarray(qn)[:, 1:], np.asarray(qx)[:, 1:])


def test_ops_star_export_clean():
    assert not hasattr(pt, "jnp")
    import paddle_tpu.ops as ops
    assert "jnp" not in ops.__all__ and "jax" not in ops.__all__
    assert "matmul" in ops.__all__ and "concat" in ops.__all__
