"""int8-quantized KV cache (reference: the masked-MHA kernel's
cache_kv_quant path; SURVEY §2.1 fused kernels / L10 serving).

Decode is HBM-bandwidth-bound (docs/BENCH.md), so int8 caches halve the
dominant traffic.  Contract: per-(position, head) symmetric scales;
quantized decode tracks the f32-cache decode closely."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate.nn.functional import masked_multihead_attention
from paddle_tpu.models.generation import make_dense_caches
from paddle_tpu.models.llama import llama


class TestQuantizedMMA:
    def test_matches_fp_attention(self, rng):
        b, s_max, h, d = 2, 32, 4, 16
        kc = jnp.asarray(rng.standard_normal((b, s_max, h, d))
                         .astype("float32"))
        vc = jnp.asarray(rng.standard_normal((b, s_max, h, d))
                         .astype("float32"))
        q = jnp.asarray(rng.standard_normal((b, h, d)).astype("float32"))
        lens = jnp.asarray([20, 11], jnp.int32)

        ref, _, _ = masked_multihead_attention(q, kc, vc, lens)

        # quantize the same cache contents (the shared quantizer)
        from paddle_tpu.incubate.nn.functional import quantize_kv
        kq, ks = quantize_kv(kc)
        vq, vs = quantize_kv(vc)
        out, _, _, _, _ = masked_multihead_attention(
            q, kq, vq, lens, k_scale=ks, v_scale=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=0.05)

    def test_write_path_roundtrip(self, rng):
        b, s_max, h, d = 2, 8, 2, 16
        (kc, vc, ks, vs) = make_dense_caches(1, b, s_max, h, d, "int8")[0]
        new_k = jnp.asarray(rng.standard_normal((b, h, d))
                            .astype("float32"))
        new_v = jnp.asarray(rng.standard_normal((b, h, d))
                            .astype("float32"))
        lens = jnp.asarray([3, 5], jnp.int32)
        q = jnp.asarray(rng.standard_normal((b, h, d)).astype("float32"))
        out, kc, vc, ks, vs = masked_multihead_attention(
            q, kc, vc, lens, new_k, new_v, k_scale=ks, v_scale=vs)
        # the written slot dequantizes back to new_k within int8 precision
        got = np.asarray(kc)[0, 3].astype(np.float32) * \
            np.asarray(ks)[0, 3][:, None]
        np.testing.assert_allclose(got, np.asarray(new_k)[0], atol=0.02)
        assert kc.dtype == jnp.int8 and vs.dtype == jnp.float32


class TestGenerateInt8:
    def test_greedy_generation_tracks_fp_cache(self):
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=96)
        model.eval()
        ids = jax.random.randint(jax.random.key(0), (2, 16), 0,
                                 model.cfg.vocab_size)
        fp = model.generate(ids, max_new_tokens=24)
        q8 = model.generate(ids, max_new_tokens=24,
                            kv_cache_dtype="int8")
        assert fp.shape == q8.shape
        agree = float(np.mean(np.asarray(fp[:, 16:]) ==
                              np.asarray(q8[:, 16:])))
        # int8 cache noise may flip a near-tie late in the rollout, but
        # the sequences must track closely on a tiny model
        assert agree >= 0.75, agree

    def test_gpt_int8_generation(self):
        from paddle_tpu.models.gpt import GPTConfig, gpt
        pt.seed(0)
        m = gpt(GPTConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          max_position_embeddings=64))
        m.eval()
        ids = jax.random.randint(jax.random.key(2), (2, 8), 0, 128)
        fp = m.generate(ids, max_new_tokens=12)
        q8 = m.generate(ids, max_new_tokens=12, kv_cache_dtype="int8")
        assert fp.shape == q8.shape
        agree = float(np.mean(np.asarray(fp[:, 8:]) ==
                              np.asarray(q8[:, 8:])))
        assert agree >= 0.7, agree

    def test_logit_error_bound_teacher_forced(self):
        """The BINDING quality gate (VERDICT r3 weak #3): token agreement
        can hide a degraded cache, so bound the LOGIT error directly.
        Teacher-forced decode (same tokens fed to both cache dtypes, so
        trajectories cannot diverge) over 16 steps: per-step max |Δlogit|
        stays within a small fraction of the fp logit scale."""
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=96)
        model.eval()
        ids = jax.random.randint(jax.random.key(3), (2, 16), 0,
                                 model.cfg.vocab_size)
        toks = jax.random.randint(jax.random.key(4), (2, 16), 0,
                                  model.cfg.vocab_size)

        def rollout(dtype):
            caches = model.model.init_cache(2, 96, dtype=dtype)
            _, caches = model.model(ids, caches=caches)
            lens = jnp.full((2,), 16, jnp.int32)
            logits = []
            for t in range(16):
                h, caches = model.model(toks[:, t:t + 1], caches=caches,
                                        seq_lens=lens)
                logits.append(model.logits(h[:, -1]))
                lens = lens + 1
            return jnp.stack(logits)

        fp = rollout(jnp.float32)
        q8 = rollout("int8")
        scale = float(jnp.std(fp))
        err = float(jnp.abs(fp - q8).max()) / scale
        # int8 cache noise must stay a small perturbation of the logits,
        # not just "usually picks the same argmax"
        assert err < 0.25, f"relative logit error {err}"
        mean_err = float(jnp.abs(fp - q8).mean()) / scale
        assert mean_err < 0.05, f"mean relative logit error {mean_err}"

    def test_dtype_spelling_normalized(self):
        from paddle_tpu.models.generation import make_dense_caches
        for spelled in ("int8", jnp.int8, np.int8):
            caches = make_dense_caches(1, 1, 4, 2, 8, spelled)
            assert len(caches[0]) == 4, spelled

    def test_recompute_fallback_rejects_int8(self):
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=64)
        ids = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError):
            model.generate(ids, max_new_tokens=2, use_cache=False,
                           kv_cache_dtype="int8")

    def test_int8_cache_structure(self):
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=64)
        caches = model.model.init_cache(2, 64, dtype="int8")
        assert len(caches[0]) == 4
        k, v, ks, vs = caches[0]
        assert k.dtype == jnp.int8 and ks.shape == k.shape[:3]

    def test_prefill_quantization_consistency(self, rng):
        """Prefill-written int8 rows must dequantize to the true K/V so
        later decode steps attend to a faithful prompt."""
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=48)
        model.eval()
        ids = jax.random.randint(jax.random.key(1), (1, 12), 0,
                                 model.cfg.vocab_size)
        caches = model.model.init_cache(1, 48, dtype="int8")
        _, caches = model.model(ids, caches=caches)
        k, v, ks, vs = caches[0]
        assert bool((jnp.abs(ks[0, :12]) > 1e-9).all())   # scales written
        assert int(jnp.sum(jnp.abs(k[0, 12:]).astype(jnp.int32))) == 0
