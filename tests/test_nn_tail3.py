"""Round-3 nn tail: loss zoo + pooling/activation torch-oracle tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as pt
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def _t(x):
    return torch.tensor(np.asarray(x))


class TestLosses:
    def test_soft_margin(self, rng):
        x = rng.standard_normal((6, 4)).astype("float32")
        y = np.sign(rng.standard_normal((6, 4))).astype("float32")
        ours = float(F.soft_margin_loss(jnp.asarray(x), jnp.asarray(y)))
        ref = float(tF.soft_margin_loss(_t(x), _t(y)))
        assert abs(ours - ref) < 1e-5

    def test_multi_margin(self, rng):
        x = rng.standard_normal((6, 5)).astype("float32")
        y = rng.integers(0, 5, 6)
        ours = float(F.multi_margin_loss(jnp.asarray(x), jnp.asarray(y)))
        ref = float(tF.multi_margin_loss(_t(x), torch.tensor(y)))
        assert abs(ours - ref) < 1e-5

    def test_multi_label_soft_margin(self, rng):
        x = rng.standard_normal((6, 5)).astype("float32")
        y = rng.integers(0, 2, (6, 5)).astype("float32")
        ours = float(F.multi_label_soft_margin_loss(jnp.asarray(x),
                                                    jnp.asarray(y)))
        ref = float(tF.multilabel_soft_margin_loss(_t(x), _t(y)))
        assert abs(ours - ref) < 1e-5

    def test_triplet_with_distance(self, rng):
        a, p, n = (rng.standard_normal((6, 8)).astype("float32")
                   for _ in range(3))
        ours = float(F.triplet_margin_with_distance_loss(
            jnp.asarray(a), jnp.asarray(p), jnp.asarray(n), swap=True))
        ref = float(tF.triplet_margin_with_distance_loss(
            _t(a), _t(p), _t(n), swap=True))
        assert abs(ours - ref) < 1e-5

    def test_poisson_gaussian_nll(self, rng):
        x = rng.uniform(0.1, 2.0, (6, 4)).astype("float32")
        y = rng.uniform(0.1, 4.0, (6, 4)).astype("float32")
        v = rng.uniform(0.2, 2.0, (6, 4)).astype("float32")
        ours = float(F.poisson_nll_loss(jnp.asarray(x), jnp.asarray(y),
                                        full=True))
        ref = float(tF.poisson_nll_loss(_t(x), _t(y), full=True))
        assert abs(ours - ref) < 1e-4
        ours = float(F.gaussian_nll_loss(jnp.asarray(x), jnp.asarray(y),
                                         jnp.asarray(v)))
        ref = float(tF.gaussian_nll_loss(_t(x), _t(y), var=_t(v)))
        assert abs(ours - ref) < 1e-4

    def test_sigmoid_focal_matches_torchvision_formula(self, rng):
        logit = rng.standard_normal((8, 3)).astype("float32")
        label = rng.integers(0, 2, (8, 3)).astype("float32")
        ours = float(F.sigmoid_focal_loss(jnp.asarray(logit),
                                          jnp.asarray(label),
                                          reduction="mean"))
        p = 1 / (1 + np.exp(-logit))
        ce = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        p_t = p * label + (1 - p) * (1 - label)
        ref = ce * (1 - p_t) ** 2.0
        ref = ref * (0.25 * label + 0.75 * (1 - label))
        assert abs(ours - float(ref.mean())) < 1e-5

    def test_dice_square_error(self, rng):
        probs = jax.nn.softmax(
            jnp.asarray(rng.standard_normal((4, 6, 3)).astype("float32")))
        label = jnp.asarray(rng.integers(0, 3, (4, 6, 1)))
        d = float(F.dice_loss(probs, label))
        assert 0.0 < d < 1.0
        x = rng.standard_normal(5).astype("float32")
        y = rng.standard_normal(5).astype("float32")
        np.testing.assert_allclose(
            np.asarray(F.square_error_cost(jnp.asarray(x), jnp.asarray(y))),
            (x - y) ** 2, rtol=1e-6)

    def test_npair_loss_finite_and_decreases_for_aligned(self, rng):
        a = rng.standard_normal((6, 8)).astype("float32")
        labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        bad = float(F.npair_loss(jnp.asarray(a),
                                 jnp.asarray(rng.standard_normal(
                                     (6, 8)).astype("float32")), labels))
        good = float(F.npair_loss(jnp.asarray(a) * 3, jnp.asarray(a) * 3,
                                  labels, l2_reg=0.0))
        assert np.isfinite(bad) and np.isfinite(good)

    def test_rnnt_loss_matches_torchaudio(self, rng):
        ta = pytest.importorskip("torchaudio")
        b, t, u, v = 2, 5, 3, 6
        logits = rng.standard_normal((b, t, u + 1, v)).astype("float32")
        labels = rng.integers(1, v, (b, u)).astype("int32")
        tlen = np.asarray([t, t - 1], np.int32)
        ulen = np.asarray([u, u - 1], np.int32)
        ours = float(F.rnnt_loss(jnp.asarray(logits), jnp.asarray(labels),
                                 jnp.asarray(tlen), jnp.asarray(ulen)))
        ref = float(ta.functional.rnnt_loss(
            torch.tensor(logits), torch.tensor(labels.astype(np.int32)),
            torch.tensor(tlen), torch.tensor(ulen), blank=0,
            reduction="mean"))
        assert abs(ours - ref) < 1e-3, (ours, ref)

    def test_rnnt_loss_brute_force_oracle(self, rng):
        """Exact check: enumerate every monotone (T,U) alignment path and
        logsumexp their probabilities (tiny lattice, no torchaudio
        needed)."""
        import itertools
        from scipy.special import log_softmax, logsumexp
        b, t, u, v = 1, 3, 2, 4
        logits = rng.standard_normal((b, t, u + 1, v)).astype("float32")
        labels = np.asarray([[2, 3]], np.int32)
        logp = log_softmax(logits.astype(np.float64), axis=-1)

        # a path is a sequence of T blanks and U emits (the last step must
        # be the final blank at (T-1, U)); enumerate interleavings
        paths = []
        for emit_positions in itertools.combinations(range(t + u - 1), u):
            lp, ti, ui, ok = 0.0, 0, 0, True
            for s in range(t + u):
                if s < t + u - 1 and s in emit_positions:
                    if ui >= u:
                        ok = False
                        break
                    lp += logp[0, ti, ui, labels[0, ui]]
                    ui += 1
                else:
                    if ti >= t:
                        ok = False
                        break
                    lp += logp[0, ti, ui, 0]
                    ti += 1
            if ok and ti == t and ui == u:
                paths.append(lp)
        ref = -logsumexp(paths)
        ours = float(F.rnnt_loss(jnp.asarray(logits), jnp.asarray(labels),
                                 jnp.asarray([t]), jnp.asarray([u]),
                                 reduction="none")[0])
        assert abs(ours - ref) < 1e-3, (ours, ref)

    def test_loss_classes(self, rng):
        x = rng.standard_normal((4, 3)).astype("float32")
        y = np.sign(rng.standard_normal((4, 3))).astype("float32")
        cls = nn.SoftMarginLoss(reduction="sum")
        fnv = F.soft_margin_loss(jnp.asarray(x), jnp.asarray(y),
                                 reduction="sum")
        assert abs(float(cls(jnp.asarray(x), jnp.asarray(y)))
                   - float(fnv)) < 1e-6


class TestPoolingActivation:
    def test_lp_pool(self, rng):
        x = rng.standard_normal((2, 3, 12)).astype("float32")
        ours = np.asarray(F.lp_pool1d(jnp.asarray(x), 2.0, 3))
        ref = tF.lp_pool1d(_t(x), 2.0, 3).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
        x2 = np.abs(rng.standard_normal((2, 3, 8, 10))).astype("float32")
        ours = np.asarray(F.lp_pool2d(jnp.asarray(x2), 3.0, (2, 2)))
        ref = tF.lp_pool2d(_t(x2), 3.0, (2, 2)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_max_unpool1d_roundtrip(self, rng):
        x = rng.standard_normal((2, 3, 12)).astype("float32")
        tout, tidx = tF.max_pool1d(_t(x), 2, return_indices=True)
        ours = np.asarray(F.max_unpool1d(jnp.asarray(tout.numpy()),
                                         jnp.asarray(tidx.numpy()), 2))
        ref = tF.max_unpool1d(tout, tidx, 2).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    def test_max_unpool3d_roundtrip(self, rng):
        x = rng.standard_normal((2, 2, 4, 4, 4)).astype("float32")
        tout, tidx = tF.max_pool3d(_t(x), 2, return_indices=True)
        ours = np.asarray(F.max_unpool3d(jnp.asarray(tout.numpy()),
                                         jnp.asarray(tidx.numpy()), 2))
        ref = tF.max_unpool3d(tout, tidx, 2).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    def test_fractional_max_pool2d(self, rng):
        x = rng.standard_normal((2, 3, 9, 9)).astype("float32")
        out = np.asarray(F.fractional_max_pool2d(jnp.asarray(x), 4,
                                                 random_u=0.3))
        assert out.shape == (2, 3, 4, 4)
        # every output is the max of SOME input window: values must exist
        assert np.isin(out, x).all()
        out3 = np.asarray(F.fractional_max_pool3d(
            jnp.asarray(rng.standard_normal((1, 2, 6, 6, 6))
                        .astype("float32")), 3, random_u=0.7))
        assert out3.shape == (1, 2, 3, 3, 3)

    def test_gumbel_softmax(self):
        pt.seed(0)
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((5, 7)).astype("float32"))
        y = F.gumbel_softmax(x, temperature=0.5)
        np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-5)
        h = F.gumbel_softmax(x, hard=True)
        assert set(np.unique(np.asarray(h)).tolist()) <= {0.0, 1.0}
        # straight-through: gradient flows
        g = jax.grad(lambda z: F.gumbel_softmax(z, hard=True).sum())(x)
        assert np.isfinite(np.asarray(g)).all()

    def test_maxout(self, rng):
        x = rng.standard_normal((2, 6, 4)).astype("float32")
        ours = np.asarray(nn.Maxout(groups=3, axis=1)(jnp.asarray(x)))
        ref = x.reshape(2, 2, 3, 4).max(axis=2)
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    def test_misc_classes(self, rng):
        x = jnp.asarray(rng.standard_normal((2, 3, 6)).astype("float32"))
        assert nn.Softsign()(x).shape == x.shape
        assert nn.LogSoftmax()(x).shape == x.shape
        assert nn.ZeroPad1D([1, 2])(x).shape == (2, 3, 9)
        x5 = jnp.ones((1, 1, 2, 2, 2))
        assert nn.ZeroPad3D(1)(x5).shape == (1, 1, 4, 4, 4)
        m = nn.RReLU()
        m.eval()
        neg = jnp.asarray([-1.0, 2.0])
        out = np.asarray(m(neg))
        assert out[1] == 2.0 and out[0] < 0.0

    def test_spectral_norm_layer(self, rng):
        w = jnp.asarray(rng.standard_normal((4, 6)).astype("float32"))
        sn = nn.SpectralNorm(w.shape, power_iters=20)
        out = np.asarray(sn(w))
        s = np.linalg.svd(np.asarray(w), compute_uv=False)[0]
        np.testing.assert_allclose(np.linalg.svd(out, compute_uv=False)[0],
                                   1.0, rtol=1e-3)
