"""Deviceless TPU compile of the FLASH-chunk ring — the only coverage
of Pallas-kernels-under-SPMD-partitioning possible without a pod.

Guards the two M107 multi-chip ring bugs (PartitionId from
lax.axis_index under partial-manual shard_map; Mosaic kernels landing
in the SPMD partitioner when any mesh axis stays auto): both only
reproduce when compiling FOR a multi-chip TPU topology with the Pallas
pack registered — the CPU test mesh never sees them.

~12 s: one tiny llama (2 layers) + ring(sep2) x ZeRO-3(2) AOT compile
against a deviceless v5e:2x2 topology.
"""

import dataclasses  # noqa: F401 — mirrors memproof's config handling
import os

import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    os.environ.get("PDTPU_SKIP_DEVICELESS") == "1",
    reason="deviceless TPU compile disabled by env")


def test_flash_ring_compiles_for_multichip_tpu(monkeypatch):
    from jax.experimental import topologies
    from jax.sharding import NamedSharding

    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, causal_lm_loss, llama

    # chunk is 256 here; drop the ring's flash threshold so the Pallas
    # path (the thing under test) is what compiles
    monkeypatch.setenv("PDTPU_RING_FLASH_MIN_CHUNK", "64")
    # the suite pins the PROCESS backend to cpu (conftest), but we are
    # compiling FOR a TPU topology: treat the dispatch backend as tpu so
    # the kernel registry serves the Pallas entry being tested
    from paddle_tpu.ops import dispatch
    monkeypatch.setattr(dispatch, "_backend", lambda: "tpu")

    td = topologies.get_topology_desc(platform="tpu",
                                      topology_name="v5e:2x2")
    fleet._reset()
    try:
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"sharding_degree": 2, "sep_degree": 2}
        fleet.init(is_collective=True, strategy=s, devices=list(td.devices))
        cfg = LlamaConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=2,
                          num_key_value_heads=2, vocab_size=256,
                          max_position_embeddings=512, dtype="bfloat16",
                          context_parallel="ring")
        with nn.meta_init():
            model = llama(cfg)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
        step = TrainStep(model, causal_lm_loss, opt, zero_stage=3)
        astate = step.abstract_state()
        bsh = NamedSharding(step.mesh, step.batch_spec)
        batch = {"input_ids": jax.ShapeDtypeStruct((2, 512), jnp.int32,
                                                   sharding=bsh),
                 "labels": jax.ShapeDtypeStruct((2, 512), jnp.int64,
                                                sharding=bsh)}
        compiled = step.lower(astate, batch).compile()
        # the Pallas kernel must actually BE in the program (flash path
        # engaged, not the einsum fallback silently covering for it)
        hlo = compiled.as_text()
        assert "tpu_custom_call" in hlo, \
            "flash ring did not engage — einsum fallback compiled instead"
        ma = compiled.memory_analysis()
        assert ma.temp_size_in_bytes > 0
    finally:
        fleet._reset()


def test_flash_ring_with_mp_head_sharding(monkeypatch):
    """The hspec path: heads sharded over mp WHILE the flash ring runs —
    exercises the manual-over-all axis set with a >1 mp axis."""
    from jax.experimental import topologies
    from jax.sharding import NamedSharding

    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, causal_lm_loss, llama

    monkeypatch.setenv("PDTPU_RING_FLASH_MIN_CHUNK", "64")
    from paddle_tpu.ops import dispatch
    monkeypatch.setattr(dispatch, "_backend", lambda: "tpu")

    td = topologies.get_topology_desc(platform="tpu",
                                      topology_name="v5e:2x2")
    fleet._reset()
    try:
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"mp_degree": 2, "sep_degree": 2}
        fleet.init(is_collective=True, strategy=s, devices=list(td.devices))
        cfg = LlamaConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=4, vocab_size=256,
                          max_position_embeddings=512, dtype="bfloat16",
                          context_parallel="ring")
        with nn.meta_init():
            model = llama(cfg)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
        step = TrainStep(model, causal_lm_loss, opt)
        astate = step.abstract_state()
        bsh = NamedSharding(step.mesh, step.batch_spec)
        batch = {"input_ids": jax.ShapeDtypeStruct((2, 512), jnp.int32,
                                                   sharding=bsh),
                 "labels": jax.ShapeDtypeStruct((2, 512), jnp.int64,
                                                sharding=bsh)}
        compiled = step.lower(astate, batch).compile()
        assert "tpu_custom_call" in compiled.as_text(), \
            "flash ring with mp head sharding did not engage"
    finally:
        fleet._reset()


def test_int4_kernel_compiles_for_multichip_mp(monkeypatch):
    """The int4 dequant kernel under an mp mesh: the column-parallel
    layer routes through an explicit shard_map (GSPMD cannot partition
    Mosaic kernels); the generic weight_only_linear entry and the
    row-parallel layer fall back to XLA under a mesh.  Both must COMPILE
    for a real multichip TPU topology."""
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as pt
    import paddle_tpu.nn.quant as QN
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mp_layers import (ColumnParallelLinear,
                                                  RowParallelLinear)
    from paddle_tpu.nn.layer import functional_call, raw_params

    monkeypatch.setattr(QN, "_use_int4_kernel", lambda: True)
    # spy: the column layer must actually ENGAGE the shard_map path (a
    # stale branch condition silently compiling the XLA fallback would
    # keep this test green for no coverage)
    engaged = []
    real = QN._int4_kernel_column_sharded

    def spy(*a, **k):
        engaged.append(1)
        return real(*a, **k)
    monkeypatch.setattr(QN, "_int4_kernel_column_sharded", spy)

    td = topologies.get_topology_desc(platform="tpu",
                                      topology_name="v5e:2x2")
    fleet._reset()
    try:
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"mp_degree": 2, "dp_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=s,
                         devices=list(td.devices))
        pt.seed(0)
        col = QN.QuantizedColumnParallelLinear(
            ColumnParallelLinear(256, 512, has_bias=False),
            algo="weight_only_int4")
        row = QN.QuantizedRowParallelLinear(
            RowParallelLinear(512, 256, has_bias=False),
            algo="weight_only_int4")

        def fwd(params, x):
            h = functional_call(col, {k[4:]: v for k, v in params.items()
                                      if k.startswith("col.")}, x)
            return functional_call(row, {k[4:]: v for k, v in params.items()
                                         if k.startswith("row.")}, h)

        params = {**{f"col.{k}": v for k, v in raw_params(col).items()},
                  **{f"row.{k}": v for k, v in raw_params(row).items()}}
        ps = {k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype,
                                      sharding=NamedSharding(hcg.mesh, P()))
              for k, v in params.items()}
        xs = jax.ShapeDtypeStruct((2, 1, 256), jnp.bfloat16,
                                  sharding=NamedSharding(hcg.mesh, P()))
        with hcg.mesh:
            jax.jit(fwd).lower(ps, xs).compile()   # must not raise
        assert engaged, "column layer never took the shard_map kernel path"
    finally:
        fleet._reset()
