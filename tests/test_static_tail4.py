"""Round-4 static tail: static.nn module (builders + (padded, length)
sequence ops), py_func/static_pylayer, program state, EMA, places/guards.

Reference: python/paddle/static/nn/* — the sequence ops here follow the
repo's documented (padded, length) redesign of LoD (static/nn.py
docstring).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.static as S


class TestModuleForm:
    def test_static_nn_is_module(self):
        import importlib
        m = importlib.import_module("paddle_tpu.static.nn")
        assert S.nn is m
        for name in ("fc embedding batch_norm layer_norm conv2d "
                     "conv2d_transpose sequence_pad sequence_pool py_func "
                     "static_pylayer while_loop cond").split():
            assert callable(getattr(S.nn, name)), name


class TestBuilders:
    def test_fc_in_program(self):
        with S.program_guard(S.Program()):
            x = S.data("x", [2, 4])
            y = S.nn.fc(x, 3, activation="relu")
            out = S.Executor().run(feed={"x": np.ones((2, 4), np.float32)},
                                   fetch_list=[y])
        assert out[0].shape == (2, 3) and out[0].min() >= 0

    def test_conv_and_norm_builders_eager(self):
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 3, 8, 8).astype(np.float32))
        y = S.nn.conv2d(x, 6, 3, padding=1, act="relu")
        assert y.shape == (2, 6, 8, 8) and np.asarray(y).min() >= 0
        z = S.nn.layer_norm(x, begin_norm_axis=1)
        np.testing.assert_allclose(np.asarray(z).mean(axis=(1, 2, 3)), 0.0,
                                   atol=1e-4)
        g = S.nn.group_norm(x, groups=3)
        assert g.shape == x.shape
        b = S.nn.batch_norm(x, is_test=True)
        assert b.shape == x.shape
        e = S.nn.embedding(jnp.asarray([[1, 2], [3, 4]]), (10, 5))
        assert e.shape == (2, 2, 5)

    def test_data_norm_default_stats_identity(self):
        x = jnp.asarray(np.random.RandomState(1).randn(4, 6)
                        .astype(np.float32))
        # defaults: mean 0, var 1 → output ≈ input
        np.testing.assert_allclose(np.asarray(S.nn.data_norm(x)),
                                   np.asarray(x), atol=1e-4)

    def test_spectral_norm_unit_sigma(self):
        w = np.random.RandomState(2).randn(6, 4).astype(np.float32)
        wn = np.asarray(S.nn.spectral_norm(jnp.asarray(w), power_iters=50))
        assert abs(np.linalg.svd(wn, compute_uv=False)[0] - 1.0) < 1e-3

    def test_row_conv_lookahead_only(self):
        x = np.zeros((1, 5, 2), np.float32)
        x[0, 3] = 1.0  # impulse at t=3
        out = np.asarray(S.nn.row_conv(jnp.asarray(x), 2))
        # averaging filter 1/3: t=1..3 see the impulse, t=4 does not
        assert out[0, 4].max() == 0.0
        np.testing.assert_allclose(out[0, 1:4], 1 / 3, atol=1e-6)

    def test_prelu_modes(self):
        x = jnp.asarray(np.array([[-4.0, 8.0]], np.float32))
        np.testing.assert_allclose(np.asarray(S.nn.prelu(x, "all")),
                                   [[-1.0, 8.0]])

    def test_nce_positive_loss_and_shape(self):
        x = jnp.asarray(np.random.RandomState(3).randn(5, 8)
                        .astype(np.float32))
        lab = jnp.asarray([0, 1, 2, 3, 4])
        loss = S.nn.nce(x, lab, num_total_classes=50, num_neg_samples=5,
                        seed=3)
        assert loss.shape == (5, 1) and np.asarray(loss).min() > 0


class TestSequenceOps:
    @pytest.fixture
    def padded(self):
        flat = np.arange(10.0, dtype=np.float32).reshape(5, 2)
        return S.nn.sequence_pad(flat, 0.0, maxlen=3, length=[2, 3])

    def test_pad_unpad_roundtrip(self, padded):
        x, ln = padded
        assert x.shape == (2, 3, 2)
        assert np.asarray(x)[0, 2].max() == 0.0  # padded slot
        flat = S.nn.sequence_unpad(x, ln)
        np.testing.assert_allclose(np.asarray(flat),
                                   np.arange(10.0).reshape(5, 2))

    def test_pool_variants(self, padded):
        x, ln = padded
        xn = np.asarray(x)
        np.testing.assert_allclose(np.asarray(S.nn.sequence_pool(x, "sum", ln)),
                                   [xn[0, :2].sum(0), xn[1].sum(0)], atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(S.nn.sequence_pool(x, "average", ln)),
            [xn[0, :2].mean(0), xn[1].mean(0)], atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(S.nn.sequence_pool(x, "max", ln)),
            [xn[0, :2].max(0), xn[1].max(0)], atol=1e-5)
        np.testing.assert_allclose(np.asarray(S.nn.sequence_last_step(x, ln)),
                                   [xn[0, 1], xn[1, 2]], atol=1e-6)
        np.testing.assert_allclose(np.asarray(S.nn.sequence_first_step(x)),
                                   xn[:, 0], atol=1e-6)

    def test_softmax_masks_padding(self, padded):
        x, ln = padded
        p = np.asarray(S.nn.sequence_softmax(x, ln))
        np.testing.assert_allclose(p[0, :2].sum(0), 1.0, atol=1e-5)
        assert p[0, 2].max() == 0.0
        np.testing.assert_allclose(p[1].sum(0), 1.0, atol=1e-5)

    def test_reverse_valid_prefix(self, padded):
        x, ln = padded
        r = np.asarray(S.nn.sequence_reverse(x, ln))
        xn = np.asarray(x)
        np.testing.assert_allclose(r[0, 0], xn[0, 1])
        np.testing.assert_allclose(r[0, 1], xn[0, 0])
        np.testing.assert_allclose(r[0, 2], xn[0, 2])  # padding untouched
        np.testing.assert_allclose(r[1], xn[1, ::-1])

    def test_concat_packs_back_to_back(self):
        a = jnp.asarray(np.ones((2, 2, 1), np.float32))
        b = jnp.asarray(2 * np.ones((2, 2, 1), np.float32))
        out, ln = S.nn.sequence_concat([a, b], [jnp.asarray([1, 2]),
                                                jnp.asarray([2, 1])])
        assert ln.tolist() == [3, 3]
        np.testing.assert_allclose(np.asarray(out)[0, :3, 0], [1, 2, 2])
        np.testing.assert_allclose(np.asarray(out)[1, :3, 0], [1, 1, 2])

    def test_expand_and_reshape(self):
        x = np.array([[1.0], [2.0]], np.float32)
        e = S.nn.sequence_expand(x, [2, 3])
        np.testing.assert_allclose(np.asarray(e)[:, 0], [1, 1, 2, 2, 2])
        r = S.nn.sequence_reshape(np.arange(12.0).reshape(6, 2), 4)
        assert r.shape == (3, 4)

    def test_enumerate_windows(self):
        ids = jnp.asarray([[1, 2, 3]])
        w = np.asarray(S.nn.sequence_enumerate(ids, 2, pad_value=0))
        np.testing.assert_array_equal(w[0], [[1, 2], [2, 3], [3, 0]])

    def test_slice_and_scatter(self):
        x = jnp.asarray(np.arange(12.0, np.float32).reshape(2, 6, 1)
                        if False else
                        np.arange(12.0).reshape(2, 6, 1).astype(np.float32))
        sl = np.asarray(S.nn.sequence_slice(x, [1, 2], [2, 2]))
        np.testing.assert_allclose(sl[0, :, 0], [1, 2])
        np.testing.assert_allclose(sl[1, :, 0], [8, 9])
        sc = np.asarray(S.nn.sequence_scatter(
            x, jnp.asarray([[0], [5]]), jnp.asarray([[[10.0]], [[10.0]]])))
        assert sc[0, 0, 0] == 10.0 and sc[1, 5, 0] == 21.0

    def test_sequence_conv_shape_and_mask(self):
        x = jnp.asarray(np.random.RandomState(4).randn(2, 5, 3)
                        .astype(np.float32))
        out = S.nn.sequence_conv(x, 4, filter_size=3,
                                 length=jnp.asarray([3, 5]))
        assert out.shape == (2, 5, 4)
        assert np.abs(np.asarray(out)[0, 3:]).max() == 0.0


class TestPyFuncAndPylayer:
    def test_py_func_forward(self):
        out_t = jax.ShapeDtypeStruct((3,), np.float32)
        y = S.nn.py_func(lambda a: (np.asarray(a) * 2).astype(np.float32),
                         jnp.ones((3,), jnp.float32), out_t)
        np.testing.assert_allclose(np.asarray(y), 2.0)

    def test_py_func_under_jit(self):
        f = jax.jit(lambda v: S.nn.py_func(
            lambda a: (np.asarray(a) * 2).astype(np.float32), v,
            jax.ShapeDtypeStruct((3,), np.float32)))
        np.testing.assert_allclose(np.asarray(f(jnp.ones((3,)))), 2.0)

    def test_py_func_backward(self):
        def fwd(a):
            return (np.asarray(a) ** 2).astype(np.float32)

        def bwd(a, g):
            return (2 * np.asarray(a) * np.asarray(g)).astype(np.float32)

        gr = jax.grad(lambda v: S.nn.py_func(
            fwd, v, jax.ShapeDtypeStruct((1,), np.float32), bwd).sum())(
                jnp.asarray([3.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(gr), [6.0])

    def test_static_pylayer(self):
        out = S.nn.static_pylayer(lambda a: a * 3, [jnp.asarray(2.0)])
        assert float(out) == 6.0
        g = jax.grad(lambda v: S.nn.static_pylayer(
            lambda a: a * a, [v], lambda ct: 5.0 * ct))(jnp.asarray(2.0))
        assert float(g) == 5.0


class TestStaticTail:
    def test_variable_alias(self):
        assert S.Variable is S.Var

    def test_places_and_guards(self):
        assert len(S.cuda_places([0, 1])) == 2
        assert S.xpu_places is S.cuda_places
        with S.device_guard("cpu"):
            pass
        with S.ipu_shard_guard(0):
            pass

    def test_program_state_roundtrip(self, tmp_path):
        prog = S.Program()
        S.set_program_state(prog, {"a": np.ones(3, np.float32)})
        path = str(tmp_path / "m")
        S.save(prog, path)
        prog2 = S.Program()
        S.load(prog2, path)
        np.testing.assert_allclose(np.asarray(prog2.params["a"]), 1.0)
        st = S.load_program_state(path)
        assert "a" in st

    def test_normalize_program(self):
        prog = S.Program()
        with S.program_guard(prog):
            x = S.data("x", [2, 2])
            y = x + 1.0
        out = S.normalize_program(prog, [x], [y])
        assert out is prog and prog._normalized_io[0] == ["x"]

    def test_weight_norm_param_attr(self):
        a = S.WeightNormParamAttr(dim=0, name="w")
        assert a.dim == 0 and a.trainable

    def test_ema_debias_and_converge(self):
        ema = S.ExponentialMovingAverage(0.9)
        p = {"w": jnp.asarray(10.0)}
        out = ema.update(p)
        np.testing.assert_allclose(float(out["w"]), 10.0, rtol=1e-6)
        for _ in range(60):
            out = ema.update(p)
        np.testing.assert_allclose(float(out["w"]), 10.0, rtol=1e-4)
        with ema.apply() as shadow:
            assert "w" in shadow
        ema.restore()


class TestBuilderParamsTracked:
    """ADVICE r4 (medium): nce/sequence_conv/prelu/row_conv must create
    TRACKED parameters — registered on the active Program so static.save
    persists them — not frozen seeded constants."""

    def test_builders_register_params(self):
        import paddle_tpu.static as static
        import paddle_tpu.static.nn as snn

        with static.program_guard(static.Program()):
            x = jnp.ones((4, 8))
            lab = jnp.zeros((4, 1), jnp.int32)
            snn.nce(x, lab, 16, num_neg_samples=4, seed=3)
            snn.prelu(jnp.ones((2, 3, 4, 4)) * -1.0, mode="channel")
            snn.sequence_conv(jnp.ones((2, 5, 8)), 6)
            snn.row_conv(jnp.ones((2, 5, 8)), 2)
            names = sorted(static.default_main_program().params)
        for tag in ("nce", "prelu", "sequence_conv", "row_conv"):
            assert any(tag in n for n in names), (tag, names)
        # nce registers weight AND bias
        assert sum(n.startswith("nce_") for n in names) == 2, names

    def test_prelu_channel_mode_nchw(self):
        import paddle_tpu.static as static
        import paddle_tpu.static.nn as snn

        with static.program_guard(static.Program()):
            y = snn.prelu(jnp.full((2, 3, 4, 4), -1.0), mode="channel")
        # alpha init 0.25, negative input: y = -0.25 everywhere
        np.testing.assert_allclose(np.asarray(y), -0.25)


class TestObjectCollectiveSizing:
    """ADVICE r4 (low): object collectives size the byte buffer to the
    pickle (256-B multiples), not a fixed 1 MB pad, and large objects
    are no longer rejected."""

    def test_small_object_small_buffer(self):
        from paddle_tpu.distributed.misc import _obj_to_padded
        buf = _obj_to_padded({"a": 1})
        assert buf.shape[0] <= 256 + 8, buf.shape

    def test_large_object_roundtrip(self):
        from paddle_tpu.distributed.misc import (_obj_to_padded,
                                                 _padded_to_obj)
        big = list(range(400_000))        # pickles well past the old 1 MB
        assert _padded_to_obj(_obj_to_padded(big)) == big

    def test_all_gather_object_world1(self):
        import paddle_tpu.distributed as dist
        out = []
        dist.all_gather_object(out, {"rank": 0, "blob": "x" * 2_000_000})
        assert out[0]["rank"] == 0 and len(out[0]["blob"]) == 2_000_000


def test_destroy_process_group_subgroup_noop(monkeypatch):
    """ADVICE r4 (low): destroying a subgroup must NOT tear down the
    global jax.distributed bootstrap."""
    import paddle_tpu.distributed as dist

    calls = []
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: calls.append(1))
    dist.destroy_process_group(group=object())
    assert not calls
    dist.destroy_process_group()
    assert calls == [1]
