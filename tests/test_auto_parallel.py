"""Auto-parallel surface (reference: python/paddle/distributed/auto_parallel
api.py shard_tensor/reshard/Partial placements + shard_dataloader;
test/auto_parallel/ in the reference tree)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh24():
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("x", "y"))


@pytest.fixture
def mesh8():
    return Mesh(np.asarray(jax.devices()[:8]), ("x",))


class TestShardReshard:
    def test_shard_and_back(self, mesh24):
        x = jnp.arange(32.0).reshape(8, 4)
        s = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Replicate()])
        assert "x" in str(s.sharding.spec)
        back = dist.reshard(s, mesh24, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_mesh_to_mesh_reshard(self, mesh24, mesh8):
        """Same devices, different mesh topology (2x4 -> 1d 8)."""
        x = jnp.arange(64.0).reshape(8, 8)
        a = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Shard(1)])
        b = dist.reshard(a, mesh8, [dist.Shard(1)])
        assert b.sharding.mesh.axis_names == ("x",)
        np.testing.assert_allclose(np.asarray(b), np.asarray(x))

    def test_uneven_shard_raises_loudly(self, mesh8):
        """XLA tiles evenly; a ragged dim must error with the fix named,
        never silently repartition (reference reshard supports ragged
        tails — documented deviation)."""
        x = jnp.arange(30.0).reshape(10, 3)
        with pytest.raises(ValueError, match="even tiles"):
            dist.shard_tensor(x, mesh8, [dist.Shard(0)])
        # a divisible dim shards fine
        y = jnp.arange(48.0).reshape(16, 3)
        s = dist.shard_tensor(y, mesh8, [dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(s), np.asarray(y))

    def test_dtype_preserved(self, mesh8):
        for dtype in (jnp.bfloat16, jnp.int32, jnp.float32):
            x = jnp.ones((8, 2), dtype)
            s = dist.shard_tensor(x, mesh8, [dist.Shard(0)])
            assert s.dtype == dtype
            assert dist.reshard(s, mesh8, [dist.Replicate()]).dtype == dtype

    def test_double_shard_one_dim(self, mesh24):
        """Shard the same tensor dim over both mesh axes."""
        x = jnp.arange(16.0).reshape(16, 1)
        s = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(s), np.asarray(x))


class TestPartial:
    def test_partial_is_not_silently_replicated(self, mesh8):
        x = jnp.ones((4, 4))
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        assert isinstance(p, dist.PartialTensor)
        with pytest.raises(RuntimeError, match="pending reduction"):
            _ = p + 1.0
        with pytest.raises(RuntimeError, match="pending reduction"):
            np.asarray(p)

    def test_partial_reduces_on_reshard(self, mesh8):
        x = jnp.full((4, 4), 5.0)
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        out = dist.reshard(p, mesh8, [dist.Replicate()])
        # rank 0 holds x, others the identity: the sum is exactly x
        np.testing.assert_allclose(np.asarray(out), 5.0)

    def test_partial_to_shard(self, mesh8):
        x = jnp.arange(16.0).reshape(16, 1)
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        out = dist.reshard(p, mesh8, [dist.Shard(0)])
        assert "x" in str(out.sharding.spec)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_partial_mean_max(self, mesh8):
        x = jnp.full((2, 2), 3.0)
        for rt in ("mean", "max", "min"):
            p = dist.shard_tensor(x, mesh8, [dist.Partial(rt)])
            out = dist.reshard(p, mesh8, [dist.Replicate()])
            np.testing.assert_allclose(np.asarray(out), 3.0)

    def test_partial_mixed_with_shard_axis(self, mesh24):
        """Partial over one mesh axis, Shard over the other."""
        x = jnp.arange(8.0).reshape(8, 1)
        p = dist.shard_tensor(x, mesh24, [dist.Partial(), dist.Shard(0)])
        assert p.axes == ("x",)
        out = dist.reshard(p, mesh24, [dist.Replicate(), dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_partial_to_partial_rejected(self, mesh8):
        p = dist.shard_tensor(jnp.ones(2), mesh8, [dist.Partial()])
        with pytest.raises(RuntimeError, match="no-op request"):
            dist.reshard(p, mesh8, [dist.Partial()])


class TestShardDataloader:
    def _loader(self, n=4, bs=8):
        from paddle_tpu import io

        class DS(io.Dataset):
            def __len__(self):
                return n * bs

            def __getitem__(self, i):
                return {"x": np.full((3,), float(i), np.float32),
                        "y": np.int64(i % 2)}

        return io.DataLoader(DS(), batch_size=bs)

    def test_batches_sharded_on_batch_dim(self, mesh8):
        dl = dist.shard_dataloader(self._loader(), mesh8, shard_dims="x")
        seen = 0
        for batch in dl:
            assert "x" in str(batch["x"].sharding.spec)
            assert batch["x"].shape == (8, 3)
            seen += 1
        assert seen == len(dl) == 4

    def test_input_keys_filter(self, mesh8):
        dl = dist.shard_dataloader(self._loader(), mesh8,
                                   input_keys=["x"], shard_dims="x")
        batch = next(iter(dl))
        assert "x" in str(batch["x"].sharding.spec)
        # y untouched (not placed)
        assert not hasattr(batch["y"], "sharding") or \
            batch["y"].sharding.is_fully_replicated

    def test_axis_index_and_validation(self, mesh24):
        dl = dist.shard_dataloader(self._loader(), mesh24, shard_dims=1)
        batch = next(iter(dl))
        assert "y" in str(batch["x"].sharding.spec)
        with pytest.raises(ValueError, match="not in mesh axes"):
            dist.shard_dataloader(self._loader(), mesh24, shard_dims="zz")

    def test_works_in_train_step(self, mesh8):
        """Sharded batches feed a compiled step directly."""
        from paddle_tpu import nn, optimizer
        from paddle_tpu.jit import TrainStep

        pt.seed(0)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 1)

            def forward(self, x):
                return self.fc(x)

        model = M()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = TrainStep(model, lambda m, b: nn.functional.mse_loss(
            m(b["x"]), b["y"]), opt, mesh=Mesh(
                np.asarray(jax.devices()).reshape(8), ("dp",)))
        state = step.init_state(0)
        dl = dist.shard_dataloader(self._loader(), step.mesh,
                                   shard_dims="dp")
        for batch in dl:
            batch = {"x": batch["x"],
                     "y": jnp.zeros((batch["x"].shape[0], 1))}
            state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))


class TestEngine:
    def _data(self, n_batches=4, bs=8):
        import jax

        out = []
        for i in range(n_batches):
            k = jax.random.key(i)
            x = jax.random.normal(k, (bs, 4))
            out.append({"x": x, "y": x.sum(-1, keepdims=True)})
        return out

    def _engine(self, mesh=None):
        from paddle_tpu import nn, optimizer

        pt.seed(0)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                return self.fc(x)

        model = M()
        loss = lambda m, b: pt.nn.functional.mse_loss(m(b["x"]), b["y"])
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        return dist.Engine(model, loss, opt, mesh=mesh)

    def test_fit_reduces_loss(self, mesh8):
        eng = self._engine(mesh=Mesh(np.asarray(jax.devices()), ("dp",)))
        data = self._data()
        first = eng.evaluate(data)["loss"]
        eng.fit(data, epochs=5)
        assert eng.evaluate(data)["loss"] < 0.5 * first

    def test_predict_shapes(self):
        eng = self._engine()
        preds = eng.predict(self._data(2))
        assert len(preds) == 2 and preds[0].shape == (8, 1)

    def test_save_load_roundtrip(self, tmp_path):
        eng = self._engine()
        eng.fit(self._data(1), epochs=1)
        eng.save(str(tmp_path / "ckpt"))
        eng2 = self._engine()
        eng2.load(str(tmp_path / "ckpt"))
        np.testing.assert_allclose(
            np.asarray(eng2.state["params"]["fc.weight"]),
            np.asarray(eng.state["params"]["fc.weight"]))

    def test_dist_to_static_surface(self):
        from paddle_tpu import nn, optimizer

        pt.seed(0)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                return self.fc(x)

        model = M()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        dm = dist.to_static(
            model, loss=lambda m, b: pt.nn.functional.mse_loss(
                m(b["x"]), b["y"]), optimizer=opt)
        batch = self._data(1)[0]
        losses = [float(dm(batch)) for _ in range(5)]
        assert losses[-1] < losses[0]
        assert "fc.weight" in dm.state_dict()
        dm.eval()
        assert np.isfinite(float(dm(batch)))

    def test_save_load_resumes_optimizer_state(self, tmp_path):
        """Resume must restore moments + step, not just params."""
        from paddle_tpu import optimizer
        eng = self._engine()
        eng.fit(self._data(2), epochs=2)
        step_before = int(eng.state["step"])
        eng.save(str(tmp_path / "full"))
        eng2 = self._engine()
        eng2.load(str(tmp_path / "full"))
        assert int(eng2.state["step"]) == step_before
        np.testing.assert_allclose(
            np.asarray(eng2.state["opt"]["step"]),
            np.asarray(eng.state["opt"]["step"]))

    def test_inference_only_engine_load(self, tmp_path):
        from paddle_tpu import nn

        eng = self._engine()
        eng.fit(self._data(1), epochs=1)
        eng.save(str(tmp_path / "ck"))
        pt.seed(7)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                return self.fc(x)

        infer = dist.Engine(M())        # no loss/optimizer
        infer.load(str(tmp_path / "ck"))
        np.testing.assert_allclose(
            np.asarray(infer.model.fc.weight),
            np.asarray(eng.state["params"]["fc.weight"]))
        preds = infer.predict(self._data(1))
        assert preds[0].shape == (8, 1)

    def test_mid_fit_validation_survives_donation(self):
        """valid_data= triggers evaluate() mid-fit while the state buffers
        are being donated each step — must not read donated arrays."""
        eng = self._engine()
        data = self._data(2)
        out = eng.fit(data, epochs=2, valid_data=data)
        assert np.isfinite(out["eval_loss"])
