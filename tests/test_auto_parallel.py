"""Auto-parallel surface (reference: python/paddle/distributed/auto_parallel
api.py shard_tensor/reshard/Partial placements + shard_dataloader;
test/auto_parallel/ in the reference tree)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh24():
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("x", "y"))


@pytest.fixture
def mesh8():
    return Mesh(np.asarray(jax.devices()[:8]), ("x",))


class TestShardReshard:
    def test_shard_and_back(self, mesh24):
        x = jnp.arange(32.0).reshape(8, 4)
        s = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Replicate()])
        assert "x" in str(s.sharding.spec)
        back = dist.reshard(s, mesh24, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_mesh_to_mesh_reshard(self, mesh24, mesh8):
        """Same devices, different mesh topology (2x4 -> 1d 8)."""
        x = jnp.arange(64.0).reshape(8, 8)
        a = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Shard(1)])
        b = dist.reshard(a, mesh8, [dist.Shard(1)])
        assert b.sharding.mesh.axis_names == ("x",)
        np.testing.assert_allclose(np.asarray(b), np.asarray(x))

    def test_uneven_shard_raises_loudly(self, mesh8):
        """XLA tiles evenly; a ragged dim must error with the fix named,
        never silently repartition (reference reshard supports ragged
        tails — documented deviation)."""
        x = jnp.arange(30.0).reshape(10, 3)
        with pytest.raises(ValueError, match="even tiles"):
            dist.shard_tensor(x, mesh8, [dist.Shard(0)])
        # a divisible dim shards fine
        y = jnp.arange(48.0).reshape(16, 3)
        s = dist.shard_tensor(y, mesh8, [dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(s), np.asarray(y))

    def test_dtype_preserved(self, mesh8):
        for dtype in (jnp.bfloat16, jnp.int32, jnp.float32):
            x = jnp.ones((8, 2), dtype)
            s = dist.shard_tensor(x, mesh8, [dist.Shard(0)])
            assert s.dtype == dtype
            assert dist.reshard(s, mesh8, [dist.Replicate()]).dtype == dtype

    def test_double_shard_one_dim(self, mesh24):
        """Shard the same tensor dim over both mesh axes."""
        x = jnp.arange(16.0).reshape(16, 1)
        s = dist.shard_tensor(x, mesh24, [dist.Shard(0), dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(s), np.asarray(x))


class TestPartial:
    def test_partial_is_not_silently_replicated(self, mesh8):
        x = jnp.ones((4, 4))
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        assert isinstance(p, dist.PartialTensor)
        with pytest.raises(RuntimeError, match="pending reduction"):
            _ = p + 1.0
        with pytest.raises(RuntimeError, match="pending reduction"):
            np.asarray(p)

    def test_partial_reduces_on_reshard(self, mesh8):
        x = jnp.full((4, 4), 5.0)
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        out = dist.reshard(p, mesh8, [dist.Replicate()])
        # rank 0 holds x, others the identity: the sum is exactly x
        np.testing.assert_allclose(np.asarray(out), 5.0)

    def test_partial_to_shard(self, mesh8):
        x = jnp.arange(16.0).reshape(16, 1)
        p = dist.shard_tensor(x, mesh8, [dist.Partial()])
        out = dist.reshard(p, mesh8, [dist.Shard(0)])
        assert "x" in str(out.sharding.spec)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_partial_mean_max(self, mesh8):
        x = jnp.full((2, 2), 3.0)
        for rt in ("mean", "max", "min"):
            p = dist.shard_tensor(x, mesh8, [dist.Partial(rt)])
            out = dist.reshard(p, mesh8, [dist.Replicate()])
            np.testing.assert_allclose(np.asarray(out), 3.0)

    def test_partial_mixed_with_shard_axis(self, mesh24):
        """Partial over one mesh axis, Shard over the other."""
        x = jnp.arange(8.0).reshape(8, 1)
        p = dist.shard_tensor(x, mesh24, [dist.Partial(), dist.Shard(0)])
        assert p.axes == ("x",)
        out = dist.reshard(p, mesh24, [dist.Replicate(), dist.Shard(0)])
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_partial_to_partial_rejected(self, mesh8):
        p = dist.shard_tensor(jnp.ones(2), mesh8, [dist.Partial()])
        with pytest.raises(RuntimeError, match="no-op request"):
            dist.reshard(p, mesh8, [dist.Partial()])


class TestShardDataloader:
    def _loader(self, n=4, bs=8):
        from paddle_tpu import io

        class DS(io.Dataset):
            def __len__(self):
                return n * bs

            def __getitem__(self, i):
                return {"x": np.full((3,), float(i), np.float32),
                        "y": np.int64(i % 2)}

        return io.DataLoader(DS(), batch_size=bs)

    def test_batches_sharded_on_batch_dim(self, mesh8):
        dl = dist.shard_dataloader(self._loader(), mesh8, shard_dims="x")
        seen = 0
        for batch in dl:
            assert "x" in str(batch["x"].sharding.spec)
            assert batch["x"].shape == (8, 3)
            seen += 1
        assert seen == len(dl) == 4

    def test_input_keys_filter(self, mesh8):
        dl = dist.shard_dataloader(self._loader(), mesh8,
                                   input_keys=["x"], shard_dims="x")
        batch = next(iter(dl))
        assert "x" in str(batch["x"].sharding.spec)
        # y untouched (not placed)
        assert not hasattr(batch["y"], "sharding") or \
            batch["y"].sharding.is_fully_replicated

    def test_axis_index_and_validation(self, mesh24):
        dl = dist.shard_dataloader(self._loader(), mesh24, shard_dims=1)
        batch = next(iter(dl))
        assert "y" in str(batch["x"].sharding.spec)
        with pytest.raises(ValueError, match="not in mesh axes"):
            dist.shard_dataloader(self._loader(), mesh24, shard_dims="zz")

    def test_works_in_train_step(self, mesh8):
        """Sharded batches feed a compiled step directly."""
        from paddle_tpu import nn, optimizer
        from paddle_tpu.jit import TrainStep

        pt.seed(0)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 1)

            def forward(self, x):
                return self.fc(x)

        model = M()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = TrainStep(model, lambda m, b: nn.functional.mse_loss(
            m(b["x"]), b["y"]), opt, mesh=Mesh(
                np.asarray(jax.devices()).reshape(8), ("dp",)))
        state = step.init_state(0)
        dl = dist.shard_dataloader(self._loader(), step.mesh,
                                   shard_dims="dp")
        for batch in dl:
            batch = {"x": batch["x"],
                     "y": jnp.zeros((batch["x"].shape[0], 1))}
            state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
