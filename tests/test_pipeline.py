"""Pipeline-parallel tests (SURVEY.md §4: parallel == serial numerics).

Reference test pattern: test/collective/fleet/hybrid_parallel_pp_layer.py —
train a small model pipelined and compare against the single-process run.
Here the 8-device CPU mesh replaces the multi-process NCCL rig.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.pipeline import (LayerDesc, PipelineLayer,
                                             SharedLayerDesc,
                                             StackedPipelineStages)
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.llama import causal_lm_loss, llama
from paddle_tpu.nn.layer import functional_call, raw_params


@pytest.fixture(autouse=True)
def _fleet_reset():
    yield
    fleet._reset()


class Block(nn.Layer):
    """Tiny homogeneous block for engine-level tests."""

    def __init__(self, width=16):
        super().__init__()
        self.fc = nn.Linear(width, width)

    def forward(self, x):
        return x + jax.nn.tanh(self.fc(x))


def _serial_blocks(n, width, seed):
    pt.seed(seed)
    return [Block(width) for _ in range(n)]


def test_stacked_matches_serial_no_mesh():
    """pp=1 scan path == Python-loop application, identical init numerics."""
    pt.seed(7)
    stacked = StackedPipelineStages(lambda: Block(16), 4, num_stages=1)
    layers = _serial_blocks(4, 16, 7)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 16)),
                    jnp.float32)
    want = x
    for l in layers:
        want = l(want)
    got = stacked(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_pipelined_matches_serial_numerics():
    """GPipe schedule over a pp=4 mesh == serial forward."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": 4, "dp_degree": 2}
    fleet.init(strategy=strategy)
    pt.seed(7)
    stacked = StackedPipelineStages(lambda: Block(16), 8, num_stages=4,
                                    num_microbatches=4)
    layers = _serial_blocks(8, 16, 7)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 16)),
                    jnp.float32)
    want = x
    for l in layers:
        want = l(want)
    with fleet.get_hybrid_communicate_group().mesh:
        got = jax.jit(lambda p, x: functional_call(stacked, p, x))(
            raw_params(stacked), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_virtual_pipeline_chunks():
    """Interleaved layout (2 chunks/stage) == serial forward."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": 2}
    fleet.init(strategy=strategy)
    pt.seed(3)
    stacked = StackedPipelineStages(lambda: Block(8), 8, num_stages=2,
                                    num_microbatches=2,
                                    num_virtual_pipeline_stages=2)
    layers = _serial_blocks(8, 8, 3)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8)), jnp.float32)
    want = x
    for l in layers:
        want = l(want)
    with fleet.get_hybrid_communicate_group().mesh:
        got = jax.jit(lambda p, x: functional_call(stacked, p, x))(
            raw_params(stacked), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_llama_pp_train_matches_single_device():
    """Full TrainStep on a pp=2 x dp=2 x mp=2 mesh: loss trajectory matches
    the unsharded single-program run (the reference's key invariant)."""
    ids = np.random.default_rng(0).integers(0, 256, size=(4, 32))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32),
             "labels": jnp.asarray(np.roll(ids, -1, 1), jnp.int32)}

    def run(hybrid, pp_stages):
        fleet._reset()
        pt.seed(0)
        if hybrid:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = hybrid
            hcg = fleet.init(strategy=strategy)
            mesh = hcg.mesh
        else:
            mesh = None
        model = llama("tiny", num_hidden_layers=4, pipeline_stages=pp_stages,
                      num_microbatches=2)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, causal_lm_loss, opt, mesh=mesh)
        state = step.init_state(seed=0)
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    # stacked-serial single device vs pipelined mesh — identical layer math
    base = run(None, 1)
    # note: pp model stacks params; serial model must too for identical init
    base_stacked = run(None, 2)  # pp structure, no mesh: still pipelined sched
    pp = run({"pp_degree": 2, "dp_degree": 2, "mp_degree": 2}, 2)
    np.testing.assert_allclose(base_stacked, pp, rtol=2e-4)
    # and the pipelined schedule itself must match plain serial numerics
    np.testing.assert_allclose(base, pp, rtol=2e-3)


def test_llama_pp_batched_mask_finite_grads():
    """Per-example boolean masks travel through the shift register; the
    fill/drain ticks must not poison gradients with NaN (all-masked rows)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": 2}
    fleet.init(strategy=strategy)
    pt.seed(0)
    model = llama("tiny", num_hidden_layers=2, pipeline_stages=2,
                  num_microbatches=2)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 16)),
                      jnp.int32)
    # per-example causal+padding bool mask [B, 1, S, S]
    causal = jnp.tril(jnp.ones((16, 16), bool))
    pad = jnp.asarray(np.random.default_rng(1).random((4, 16)) > 0.2)
    mask = causal[None, None] & pad[:, None, None, :]
    # keep the diagonal: a fully-masked row is NaN in any execution path
    mask = mask | jnp.eye(16, dtype=bool)[None, None]
    params = raw_params(model)

    def loss(p):
        return functional_call(
            model, p, ids, labels=jnp.roll(ids, -1, 1), attn_mask=mask)

    with fleet.get_hybrid_communicate_group().mesh:
        l, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(l))
    flat = jax.tree.leaves(g)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in flat)

    # broadcast mask [1,1,S,S] must also work (demoted to a static extra)
    bmask = causal[None, None]
    with fleet.get_hybrid_communicate_group().mesh:
        l2 = jax.jit(lambda p: functional_call(
            model, p, ids, labels=jnp.roll(ids, -1, 1),
            attn_mask=bmask))(params)
    assert np.isfinite(float(l2))


def test_pipeline_layer_api():
    """PipelineLayer(LayerDescs) partitions and runs; shared descs tie."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": 2}
    fleet.init(strategy=strategy)
    pt.seed(1)

    pipe = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 16)] +
               [LayerDesc(Block, 16) for _ in range(4)] +
               [LayerDesc(nn.Linear, 16, 8)],
        num_stages=2, num_microbatches=2)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 8)), jnp.float32)
    with fleet.get_hybrid_communicate_group().mesh:
        out = jax.jit(lambda p, x: functional_call(pipe, p, x))(
            raw_params(pipe), x)
    assert out.shape == (4, 8)
    assert jnp.all(jnp.isfinite(out))

    # serial reference with the same seed
    pt.seed(1)
    pre = nn.Linear(8, 16)
    blocks = [Block(16) for _ in range(4)]
    post = nn.Linear(16, 8)
    want = post(_chain(blocks, pre(x)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def _chain(layers, x):
    for l in layers:
        x = l(x)
    return x


def test_shared_layer_desc_ties_params():
    class Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter((8, 4))

        def forward(self, ids):
            return self.weight[ids]

    def head_fwd(layer, x):
        return x @ layer.weight.T

    pipe = PipelineLayer(layers=[
        SharedLayerDesc("emb", Emb),
        LayerDesc(Block, 4),
        LayerDesc(Block, 4),
        SharedLayerDesc("emb", Emb, forward_func=head_fwd),
    ], num_stages=1)
    names = [n for n, _ in pipe.named_parameters()]
    # the shared table appears exactly once in the param pytree
    assert sum("weight" in n and "fc" not in n for n in names) == 1

    ids = jnp.asarray([0, 3, 5], jnp.int32)
    out = pipe(ids)
    assert out.shape == (3, 8)

    # gradient flows from BOTH use sites into the single shared param
    params = raw_params(pipe)
    emb_name = next(n for n in params if n.endswith("weight")
                    and "fc" not in n)

    def loss(p):
        return functional_call(pipe, p, ids).sum()

    g = jax.grad(lambda p: loss(p))(params)[emb_name]
    assert float(jnp.abs(g).sum()) > 0


class TestRematMemoryBound:
    """The module docstring's GPipe+remat claim, measured (round-1 verdict:
    'argued, not measured').  XLA's compiled memory stats give the
    activation highwater: with per-layer remat the pp=2 x 8-microbatch
    schedule must hold an order less temp memory than storing every
    activation (measured 2026-07-30: 8.3 MB vs 84.6 MB, ratio 0.098 —
    the 0.35 bar leaves margin for compiler drift while still failing if
    remat silently stops applying)."""

    @staticmethod
    def _temp_bytes(remat):
        import paddle_tpu as pt
        from paddle_tpu import optimizer
        from paddle_tpu.distributed import fleet
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.llama import causal_lm_loss, llama

        pt.seed(0)
        fleet._reset()
        st = fleet.DistributedStrategy()
        st.hybrid_configs = {"pp_degree": 2, "dp_degree": 4}
        hcg = fleet.init(is_collective=True, strategy=st)
        try:
            model = llama("tiny", num_hidden_layers=4, pipeline_stages=2,
                          num_microbatches=8, use_recompute=remat,
                          max_position_embeddings=256)
            opt = optimizer.AdamW(learning_rate=1e-3,
                                  parameters=model.parameters())
            step = TrainStep(model, causal_lm_loss, opt, mesh=hcg.mesh)
            state = step.init_state(0)
            ids = jax.random.randint(jax.random.key(0), (8, 256), 0, 256)
            batch = {"input_ids": ids, "labels": ids}
            with hcg.mesh:
                compiled = step.lower(state, batch).compile()
            return compiled.memory_analysis().temp_size_in_bytes
        finally:
            fleet._reset()

    def test_remat_bounds_activation_highwater(self):
        no_remat = self._temp_bytes(False)
        remat = self._temp_bytes(True)
        assert remat < 0.35 * no_remat, (
            f"remat temp {remat/1e6:.1f} MB vs no-remat "
            f"{no_remat/1e6:.1f} MB — recompute no longer bounds the "
            "pipeline activation highwater")


def test_llama_interleaved_pp_tied_matches_single_device():
    """Interleaved schedule (virtual_pp_degree=2) + tied embeddings at
    pp=2 on the full hybrid mesh: loss trajectory equals the unsharded
    run — the reference's production PP mode (VERDICT r4 #5a)."""
    ids = np.random.default_rng(0).integers(0, 256, size=(4, 32))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32),
             "labels": jnp.asarray(np.roll(ids, -1, 1), jnp.int32)}

    def run(hybrid, pp_stages, vpp):
        fleet._reset()
        pt.seed(0)
        if hybrid:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = hybrid
            hcg = fleet.init(strategy=strategy)
            mesh = hcg.mesh
        else:
            mesh = None
        model = llama("tiny", num_hidden_layers=8,
                      pipeline_stages=pp_stages, num_microbatches=2,
                      virtual_pp_degree=vpp, tie_word_embeddings=True)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, causal_lm_loss, opt, mesh=mesh)
        state = step.init_state(seed=0)
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    base = run(None, 1, 1)
    inter = run({"pp_degree": 2, "dp_degree": 2, "mp_degree": 2}, 2, 2)
    np.testing.assert_allclose(base, inter, rtol=2e-3)
