"""Optimizer numerics vs NumPy oracles (the reference's OpTest pattern:
test/legacy_test/test_adamw_op.py etc.)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.nn.layer import raw_params


def np_adamw(p, g, m, v, t, lr, b1, b2, eps, wd):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1**t)
    vhat = v / (1 - b2**t)
    p = p - lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)
    return p, m, v


@pytest.mark.parametrize("steps", [1, 3])
def test_adamw_matches_numpy(steps):
    rng = np.random.default_rng(0)
    p0 = rng.standard_normal((4, 3)).astype(np.float32)
    model = nn.Linear(4, 3, bias_attr=False)
    model.set_state_dict({"weight": p0})
    opt = optimizer.AdamW(learning_rate=0.01, weight_decay=0.05,
                          parameters=model.parameters())
    params = raw_params(model)
    state = opt.init(params)

    p_np, m_np, v_np = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for t in range(1, steps + 1):
        g = rng.standard_normal((4, 3)).astype(np.float32)
        params, state = opt.apply({"weight": jnp.asarray(g)}, state, params)
        p_np, m_np, v_np = np_adamw(p_np, g, m_np, v_np, t, 0.01, 0.9, 0.999,
                                    1e-8, 0.05)
    np.testing.assert_allclose(np.asarray(params["weight"]), p_np, rtol=1e-4,
                               atol=1e-5)


def test_sgd_and_momentum():
    p0 = np.ones((2, 2), dtype=np.float32)
    g = np.full((2, 2), 0.5, dtype=np.float32)
    m = nn.Linear(2, 2, bias_attr=False)
    m.set_state_dict({"weight": p0})
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    params, state = raw_params(m), None
    state = opt.init(params)
    params, state = opt.apply({"weight": jnp.asarray(g)}, state, params)
    np.testing.assert_allclose(np.asarray(params["weight"]), p0 - 0.1 * g)

    mom = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=m.parameters())
    params = {"weight": jnp.asarray(p0)}
    state = mom.init(params)
    params, state = mom.apply({"weight": jnp.asarray(g)}, state, params)
    params, state = mom.apply({"weight": jnp.asarray(g)}, state, params)
    # velocity: v1=g, v2=0.9g+g=1.9g ; p = p0 -0.1g -0.1*1.9g
    np.testing.assert_allclose(np.asarray(params["weight"]),
                               p0 - 0.1 * g - 0.1 * 1.9 * g, rtol=1e-6)


def test_multi_precision_master_weights():
    p0 = np.full((8, 8), 0.1, dtype=np.float32)
    m = nn.Linear(8, 8, bias_attr=False)
    m.set_state_dict({"weight": p0})
    m.astype("bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                          parameters=m.parameters())
    params = raw_params(m)
    state = opt.init(params)
    assert state["master"]["weight"].dtype == jnp.float32
    g = jnp.full((8, 8), 1e-3, jnp.bfloat16)
    for _ in range(10):
        params, state = opt.apply({"weight": g}, state, params)
    # master accumulates tiny updates that bf16 alone would lose
    assert params["weight"].dtype == jnp.bfloat16
    master = np.asarray(state["master"]["weight"])
    assert np.all(master < 0.1) and master.std() < 1e-6


def test_grad_clip_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
    clipped = clip(grads)
    total = np.sqrt(sum(float(jnp.sum(jnp.square(v))) for v in clipped.values()))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)
    # under the norm -> unchanged
    small = {"a": jnp.full((2,), 0.01)}
    np.testing.assert_allclose(np.asarray(clip(small)["a"]), 0.01, rtol=1e-5)


def test_apply_decay_param_fun():
    m = nn.Linear(2, 2)
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                          parameters=m.parameters(),
                          apply_decay_param_fun=lambda n: "bias" not in n)
    params = raw_params(m)
    state = opt.init(params)
    zero_g = {k: jnp.zeros_like(v) for k, v in params.items()}
    new_params, _ = opt.apply(zero_g, state, params)
    # bias had no decay and zero grad -> unchanged; weight decayed
    np.testing.assert_allclose(np.asarray(new_params["bias"]),
                               np.asarray(params["bias"]))
    assert not np.allclose(np.asarray(new_params["weight"]),
                           np.asarray(params["weight"]))


def test_lr_schedulers():
    from paddle_tpu.optimizer import lr
    warm = lr.LinearWarmup(learning_rate=1.0, warmup_steps=10, start_lr=0.0,
                           end_lr=1.0)
    assert abs(float(warm.lr_at(jnp.asarray(5))) - 0.5) < 1e-6
    assert abs(float(warm.lr_at(jnp.asarray(50))) - 1.0) < 1e-6

    cos = lr.CosineAnnealingDecay(learning_rate=1.0, T_max=100)
    assert abs(float(cos.lr_at(jnp.asarray(0))) - 1.0) < 1e-6
    assert abs(float(cos.lr_at(jnp.asarray(100)))) < 1e-6

    combo = lr.LinearWarmup(learning_rate=cos, warmup_steps=10, start_lr=0.0,
                            end_lr=1.0)
    assert abs(float(combo.lr_at(jnp.asarray(60))) -
               float(cos.lr_at(jnp.asarray(50)))) < 1e-6

    noam = lr.NoamDecay(d_model=512, warmup_steps=4000)
    v1, v2 = float(noam.lr_at(jnp.asarray(4000))), float(noam.lr_at(jnp.asarray(8000)))
    assert v1 > v2 > 0

    step = lr.StepDecay(learning_rate=1.0, step_size=10, gamma=0.1)
    assert abs(float(step.lr_at(jnp.asarray(25))) - 0.01) < 1e-6

    piece = lr.PiecewiseDecay(boundaries=[3, 6], values=[1.0, 0.5, 0.1])
    for s, expect in [(0, 1.0), (4, 0.5), (7, 0.1)]:
        assert abs(float(piece.lr_at(jnp.asarray(s))) - expect) < 1e-7

    # stateful parity surface
    sched = lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched)
    assert opt.get_lr() == 1.0
    sched.step(); sched.step()
    assert abs(opt.get_lr() - 0.5) < 1e-7


def test_eager_step_surface():
    """Paddle-style opt.step() for eager/debug use."""
    m = nn.Linear(2, 1, bias_attr=False)
    m.set_state_dict({"weight": np.ones((2, 1), np.float32)})
    opt = optimizer.SGD(learning_rate=0.5, parameters=m.parameters())
    opt.set_grads({"weight": jnp.ones((2, 1))})
    opt.step()
    np.testing.assert_allclose(np.asarray(m.weight), 0.5)


class TestOptimizerBreadth:
    """Adadelta/Adamax vs torch oracle; Orthogonal/Assign/Dirac inits."""

    def _run_opt(self, opt_cls, torch_cls, okw, tkw, steps=5):
        import jax.numpy as jnp
        import numpy as np
        import torch
        import paddle_tpu as pt
        from paddle_tpu import nn

        pt.seed(0)
        w0 = np.random.default_rng(0).normal(size=(4, 3)).astype("float32")
        g = np.random.default_rng(1).normal(size=(4, 3)).astype("float32")

        layer = nn.Linear(4, 3, bias_attr=False)
        layer.weight = jnp.asarray(w0)
        opt = opt_cls(parameters=layer.parameters(), **okw)
        params = {"weight": jnp.asarray(w0)}
        state = opt.init(params)
        for _ in range(steps):
            params, state = opt.apply({"weight": jnp.asarray(g)}, state, params)

        tw = torch.nn.Parameter(torch.tensor(w0))
        topt = torch_cls([tw], **tkw)
        for _ in range(steps):
            tw.grad = torch.tensor(g)
            topt.step()
        import numpy.testing as npt
        npt.assert_allclose(np.asarray(params["weight"]), tw.detach().numpy(),
                            rtol=2e-3, atol=2e-4)

    def test_adadelta_vs_torch(self):
        import torch
        from paddle_tpu.optimizer import Adadelta
        self._run_opt(Adadelta, torch.optim.Adadelta,
                      dict(learning_rate=1.0, rho=0.95, epsilon=1e-6),
                      dict(lr=1.0, rho=0.95, eps=1e-6))

    def test_adamax_vs_torch(self):
        import torch
        from paddle_tpu.optimizer import Adamax
        self._run_opt(Adamax, torch.optim.Adamax,
                      dict(learning_rate=0.01, beta1=0.9, beta2=0.999,
                           epsilon=1e-8),
                      dict(lr=0.01, betas=(0.9, 0.999), eps=1e-8))

    def test_orthogonal_assign_dirac(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.nn import initializer as I

        q = I.Orthogonal()(jax.random.key(0), (6, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(q.T @ q), np.eye(4),
                                   atol=1e-5)
        v = np.arange(6.0).reshape(2, 3).astype("float32")
        out = I.Assign(v)(jax.random.key(0), (2, 3), jnp.float32)
        np.testing.assert_array_equal(np.asarray(out), v)
        w = I.Dirac()(jax.random.key(0), (3, 3, 3, 3), jnp.float32)
        x = np.random.default_rng(0).normal(size=(1, 3, 5, 5)).astype("float32")
        from paddle_tpu.nn import functional as F
        y = np.asarray(F.conv2d(x, w, padding=1))
        np.testing.assert_allclose(y, x, rtol=1e-5)  # identity conv


class TestRound2Optimizers:
    """NAdam/RAdam/Rprop torch-oracle parity + ASGD averaging."""

    def _grads(self, i):
        g = (np.arange(12).reshape(4, 3).astype(np.float32) - 5.0) \
            * 0.1 * (i + 1) % 3.0 - 1.0
        return g

    def _compare(self, ours_fn, torch_fn, steps=8, tol=1e-4):
        import torch
        from paddle_tpu import optimizer as O
        w0 = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
        opt = ours_fn(O)
        params = {"w": jnp.asarray(w0)}
        state = opt.init(params)
        tw = torch.tensor(w0.copy(), requires_grad=True)
        topt = torch_fn(torch, [tw])
        for i in range(steps):
            g = self._grads(i)
            params, state = opt.apply({"w": jnp.asarray(g)}, state, params)
            tw.grad = torch.tensor(g)
            topt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), atol=tol)

    def test_nadam_vs_torch(self):
        self._compare(lambda O: O.NAdam(learning_rate=0.01),
                      lambda t, ps: t.optim.NAdam(ps, lr=0.01))

    def test_radam_vs_torch(self):
        self._compare(lambda O: O.RAdam(learning_rate=0.01),
                      lambda t, ps: t.optim.RAdam(ps, lr=0.01))

    def test_rprop_vs_torch(self):
        self._compare(lambda O: O.Rprop(learning_rate=0.01),
                      lambda t, ps: t.optim.Rprop(ps, lr=0.01))

    def test_asgd_average_tracks_iterates(self):
        from paddle_tpu import optimizer as O
        opt = O.ASGD(learning_rate=0.1)
        params = {"w": jnp.zeros(())}
        state = opt.init(params)
        iterates = []
        for _ in range(5):
            params, state = opt.apply({"w": jnp.ones(())}, state, params)
            iterates.append(float(params["w"]))
        np.testing.assert_allclose(float(state["avg"]["w"]),
                                   np.mean(iterates), rtol=1e-6)


class TestLBFGS:
    def test_rosenbrock_converges(self):
        from paddle_tpu.optimizer import LBFGS

        def rosen(p):
            x, y = p["x"], p["y"]
            return (1 - x) ** 2 + 100.0 * (y - x * x) ** 2

        opt = LBFGS(max_iter=80, line_search_fn="strong_wolfe")
        params, loss = opt.minimize(
            rosen, {"x": jnp.asarray(-1.2), "y": jnp.asarray(1.0)})
        assert loss < 1e-7
        np.testing.assert_allclose(
            [float(params["x"]), float(params["y"])], [1.0, 1.0], atol=1e-3)

    def test_step_closure_on_model(self):
        import paddle_tpu as pt
        from paddle_tpu import nn
        from paddle_tpu.nn.layer import functional_call
        from paddle_tpu.optimizer import LBFGS

        pt.seed(0)
        m = nn.Linear(4, 1)
        X = jnp.asarray(np.random.default_rng(0).normal(
            size=(64, 4)).astype(np.float32))
        Y = X @ jnp.asarray([[1.0], [-2.0], [3.0], [0.5]]) + 0.7
        opt = LBFGS(max_iter=50, line_search_fn="strong_wolfe",
                    parameters=m.parameters())
        loss = opt.step(lambda p: ((functional_call(m, p, X) - Y) ** 2)
                        .mean())
        assert loss < 1e-7
        np.testing.assert_allclose(np.asarray(m.weight)[:, 0],
                                   [1, -2, 3, 0.5], atol=1e-3)
        np.testing.assert_allclose(float(m.bias[0]), 0.7, atol=1e-3)

    def test_no_line_search_mode(self):
        from paddle_tpu.optimizer import LBFGS

        def quad(p):
            return (p["w"] ** 2).sum()

        opt = LBFGS(learning_rate=0.5, max_iter=30)
        params, loss = opt.minimize(quad, {"w": jnp.ones(3)})
        assert loss < 1e-6

    def test_bad_line_search_rejected(self):
        from paddle_tpu.optimizer import LBFGS
        with pytest.raises(ValueError, match="strong_wolfe"):
            LBFGS(line_search_fn="armijo")

    def test_weight_decay_and_signature_compat(self):
        from paddle_tpu.optimizer import LBFGS

        def quad(p):
            return ((p["w"] - 2.0) ** 2).sum()

        # reference kwargs accepted; wd pulls the optimum below 2.0
        opt = LBFGS(max_iter=40, line_search_fn="strong_wolfe",
                    weight_decay=1.0, name="lbfgs")
        params, _ = opt.minimize(quad, {"w": jnp.zeros(3)})
        w = float(params["w"][0])
        assert 1.2 < w < 1.5   # analytic optimum 2*2/(2+1) = 4/3
        with pytest.raises(NotImplementedError, match="grad_clip"):
            LBFGS(grad_clip=object())
