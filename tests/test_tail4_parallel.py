"""Round-4 features under the virtual 8-device mesh: Tensor methods
inside shard_map, new losses/ops under dp sharding, fused_moe under jit
with sharded batch, sequence ops in a dp data pipeline.

Pattern follows tests/test_*parallel*.py: parallel-vs-serial numerics.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))


class TestTensorMethodsSharded:
    def test_methods_inside_shard_map(self):
        from paddle_tpu.core.compat import shard_map
        mesh = _mesh()

        def block(x):
            return x.abs().add(x.sign()).multiply(x.sigmoid())

        x = jnp.asarray(np.random.RandomState(0).randn(16, 4)
                        .astype(np.float32))
        f = shard_map(block, mesh=mesh, in_specs=P("dp"),
                      out_specs=P("dp"))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(block(x)),
                                   rtol=1e-6)

    def test_methods_on_sharded_global_array(self):
        mesh = _mesh()
        x = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                           NamedSharding(mesh, P("dp")))
        out = jax.jit(lambda v: v.square().cumsum(0))(x)
        np.testing.assert_allclose(
            np.asarray(out), np.cumsum(np.arange(32.0).reshape(8, 4) ** 2,
                                       axis=0))


class TestLossesUnderDp:
    def test_margin_ce_dp_sharded_matches_serial(self):
        mesh = _mesh()
        rs = np.random.RandomState(1)
        cos = np.clip(rs.randn(16, 10), -0.99, 0.99).astype(np.float32)
        lab = rs.randint(0, 10, (16,))
        serial = float(F.margin_cross_entropy(jnp.asarray(cos),
                                              jnp.asarray(lab), scale=4.0))
        csh = jax.device_put(jnp.asarray(cos), NamedSharding(mesh, P("dp")))
        lsh = jax.device_put(jnp.asarray(lab), NamedSharding(mesh, P("dp")))
        par = float(jax.jit(lambda c, l: F.margin_cross_entropy(
            c, l, scale=4.0))(csh, lsh))
        assert abs(serial - par) < 1e-5

    def test_hsigmoid_dp_sharded(self):
        mesh = _mesh()
        rs = np.random.RandomState(2)
        x = rs.randn(16, 8).astype(np.float32)
        lab = rs.randint(0, 10, (16,))
        w = rs.randn(9, 8).astype(np.float32)
        serial = np.asarray(F.hsigmoid_loss(jnp.asarray(x),
                                            jnp.asarray(lab), 10,
                                            jnp.asarray(w)))
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
        ls = jax.device_put(jnp.asarray(lab), NamedSharding(mesh, P("dp")))
        par = np.asarray(jax.jit(lambda a, b: F.hsigmoid_loss(
            a, b, 10, jnp.asarray(w)))(xs, ls))
        np.testing.assert_allclose(par, serial, rtol=1e-5)

    def test_sparse_attention_under_jit_dp(self):
        mesh = _mesh()
        rs = np.random.RandomState(3)
        B, H, M, D = 8, 2, 4, 8
        q = rs.randn(B, H, M, D).astype(np.float32)
        k = rs.randn(B, H, M, D).astype(np.float32)
        v = rs.randn(B, H, M, D).astype(np.float32)
        off = np.tile(np.arange(0, 17, 4, dtype=np.int32), (B, H, 1))
        cols = np.tile(np.tile(np.arange(4, dtype=np.int32), 4), (B, H, 1))
        serial = np.asarray(F.sparse_attention(q, k, v, off, cols))
        sh = lambda a: jax.device_put(jnp.asarray(a),
                                      NamedSharding(mesh, P("dp")))
        par = np.asarray(jax.jit(F.sparse_attention)(
            sh(q), sh(k), sh(v), sh(off), sh(cols)))
        np.testing.assert_allclose(par, serial, atol=1e-5)


class TestFusedMoeUnderMesh:
    def test_dp_sharded_batch_matches_serial(self):
        from paddle_tpu.incubate.nn import functional as IF
        mesh = _mesh()
        rs = np.random.RandomState(4)
        H, I, E = 8, 16, 4
        x = rs.randn(16, H).astype(np.float32)
        gw = rs.randn(H, E).astype(np.float32)
        w1 = (rs.randn(E, H, 2 * I) / 4).astype(np.float32)
        w2 = (rs.randn(E, I, H) / 4).astype(np.float32)
        serial = np.asarray(IF.fused_moe(jnp.asarray(x), gw, w1, w2))
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
        par = np.asarray(jax.jit(lambda a: IF.fused_moe(a, gw, w1, w2))(xs))
        np.testing.assert_allclose(par, serial, atol=1e-5)


class TestSequenceOpsInPipeline:
    def test_sequence_pool_softmax_under_jit_dp(self):
        import paddle_tpu.static as S
        mesh = _mesh()
        rs = np.random.RandomState(5)
        x = rs.randn(8, 6, 4).astype(np.float32)
        ln = np.array([3, 6, 2, 4, 5, 1, 6, 3], np.int32)
        serial_pool = np.asarray(S.nn.sequence_pool(jnp.asarray(x),
                                                    "average",
                                                    jnp.asarray(ln)))
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
        ls = jax.device_put(jnp.asarray(ln), NamedSharding(mesh, P("dp")))
        par = np.asarray(jax.jit(
            lambda a, l: S.nn.sequence_pool(a, "average", l))(xs, ls))
        np.testing.assert_allclose(par, serial_pool, rtol=1e-5)
        sm = np.asarray(jax.jit(
            lambda a, l: S.nn.sequence_softmax(a, l))(xs, ls))
        np.testing.assert_allclose(sm[0, :3].sum(0), 1.0, atol=1e-5)
        assert np.abs(sm[0, 3:]).max() == 0.0

    def test_gather_tree_under_jit(self):
        ids = jnp.asarray(np.random.RandomState(6)
                          .randint(0, 9, (5, 8, 3)).astype(np.int32))
        parents = jnp.asarray(np.random.RandomState(7)
                              .randint(0, 3, (5, 8, 3)).astype(np.int32))
        serial = np.asarray(F.gather_tree(ids, parents))
        par = np.asarray(jax.jit(F.gather_tree)(ids, parents))
        np.testing.assert_array_equal(par, serial)
