"""linalg/fft importable-module parity + lu_solve/pca_lowrank.

Reference: python/paddle/linalg.py, python/paddle/fft.py (module
re-export form) — `import paddle.linalg` works there, so it must here.
"""

import importlib

import numpy as np
import pytest

import paddle_tpu as P


class TestModuleForm:
    def test_import_module_works(self):
        L = importlib.import_module("paddle_tpu.linalg")
        F = importlib.import_module("paddle_tpu.fft")
        assert P.linalg is L and P.fft is F

    def test_surface_hoisted(self):
        for name in ("svd qr cholesky solve det slogdet lu lu_unpack "
                     "svdvals ormqr householder_product svd_lowrank "
                     "cholesky_inverse matrix_exp vector_norm").split():
            assert callable(getattr(P.linalg, name)), name
        for name in ("fft ifft rfft irfft fft2 hfft2 ihfftn fftshift "
                     "fftfreq").split():
            assert callable(getattr(P.fft, name)), name


class TestLuSolve:
    def test_solves_against_numpy(self):
        rng = np.random.RandomState(0)
        a = rng.randn(5, 5).astype(np.float32) + 5 * np.eye(5, dtype=np.float32)
        b = rng.randn(5, 2).astype(np.float32)
        lu, piv = P.linalg.lu(P.to_tensor(a))
        x = np.asarray(P.linalg.lu_solve(P.to_tensor(b), lu, piv))
        np.testing.assert_allclose(a @ x, b, atol=1e-4)

    def test_trans(self):
        rng = np.random.RandomState(1)
        a = rng.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
        b = rng.randn(4, 1).astype(np.float32)
        lu, piv = P.linalg.lu(P.to_tensor(a))
        x = np.asarray(P.linalg.lu_solve(P.to_tensor(b), lu, piv, trans="T"))
        np.testing.assert_allclose(a.T @ x, b, atol=1e-4)


class TestPcaLowrank:
    def test_recovers_leading_spectrum(self):
        rng = np.random.RandomState(2)
        m = rng.randn(40, 10).astype(np.float32)
        u, s, v = P.linalg.pca_lowrank(P.to_tensor(m), q=4, niter=4)
        mc = m - m.mean(0)
        sv_true = np.linalg.svd(mc, compute_uv=False)[:4]
        # randomized method: leading values tight, trailing value loose
        np.testing.assert_allclose(np.asarray(s)[:2], sv_true[:2], rtol=0.02)
        np.testing.assert_allclose(np.asarray(s), sv_true, rtol=0.15)

    def test_shapes_and_orthonormality(self):
        rng = np.random.RandomState(3)
        m = rng.randn(20, 8).astype(np.float32)
        u, s, v = P.linalg.pca_lowrank(P.to_tensor(m), q=3)
        assert u.shape == (20, 3) and s.shape == (3,) and v.shape == (8, 3)
        np.testing.assert_allclose(np.asarray(u).T @ np.asarray(u),
                                   np.eye(3), atol=1e-4)

    def test_center_false(self):
        rng = np.random.RandomState(4)
        m = rng.randn(15, 6).astype(np.float32) + 10.0
        u, s, v = P.linalg.pca_lowrank(P.to_tensor(m), q=2, center=False,
                                       niter=4)
        sv_true = np.linalg.svd(m, compute_uv=False)[:1]
        np.testing.assert_allclose(np.asarray(s)[:1], sv_true, rtol=0.02)
