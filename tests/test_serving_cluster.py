"""Cluster control plane (paddle_tpu.serving.cluster + .worker).

The load-bearing guarantees (docs/SERVING.md "Cluster serving"):

- per-host ``ServingWorker`` loops register with the TCPStore under
  epoch-fenced leases and step their local Engine independently; the
  ``ClusterController`` owns routing/failure handling and never steps
  an engine;
- a dead worker (stale lease) is revoked and its in-flight requests
  re-enter the queues from their last ``KVHandout`` snapshots —
  token-identical where pages were already streamed, fresh re-prefill
  otherwise;
- a paused-then-resumed worker cannot act on stale ownership: its CAS
  lease-renew raises ``LeaseLost``, its commands/queue items/output
  writes carry the old epoch and are dropped or fenced;
- elasticity transitions (``role_flip`` / ``drain`` /
  ``rolling_upgrade``) ride the same evacuation machinery — zero
  recompiles, greedy token-identity across flips, kills and upgrades.

Control-plane unit tests run on fakes (no jax, no engine — fast);
the end-to-end tests drive real engines and are marked ``slow``
(the ``serving-cluster`` CI gate runs the cross-process version).
"""

import collections
import json
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu import resilience as rs
from paddle_tpu import serving
from paddle_tpu.launch.preempt import PreemptionGuard
from paddle_tpu.launch.store import TCPStore, free_port
from paddle_tpu.serving.cluster import (ClusterController, ControllerLease,
                                        LeaseLost, LeaseMonitor, StoreQueue,
                                        WorkerSpawner)
from paddle_tpu.serving.frontdoor import TenantPolicy
from paddle_tpu.serving.gateway import ClusterGateway
from paddle_tpu.serving.worker import ServingWorker
from paddle_tpu.resilience.retry import RetryPolicy

R = np.random.default_rng(0)
PROMPTS = [R.integers(0, 256, size=n).astype(np.int32)
           for n in (5, 17, 9, 26)]


@pytest.fixture
def store():
    s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
    yield s
    s.close()


@pytest.fixture(autouse=True)
def _clean():
    yield
    rs.clear_faults()
    obs.disable()


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- control-plane fakes (no jax) -------------------------------------------

class _FakeAllocator:
    def __init__(self, n):
        self.free_blocks = n


class _FakeKV:
    def __init__(self, n=8):
        self.num_blocks = n
        self.allocator = _FakeAllocator(n)


class _FakeScheduler:
    def __init__(self):
        self.slots = []
        self.waiting = collections.deque()

    def queue_depth(self):
        return len(self.waiting)

    def active(self):
        return []


class _FakeEngine:
    role = "decode"

    def __init__(self):
        self.scheduler = _FakeScheduler()
        self.kv = _FakeKV()
        self.handoffs = 0
        self.handed_off = collections.deque()
        self._states = {}
        self.lora = None
        self._warmed = True

    def has_work(self):
        return False

    def step(self):
        pass


def _fake_worker(store, wid="w0", **kw):
    kw.setdefault("retry", RetryPolicy(max_attempts=3, backoff_s=0.001))
    kw.setdefault("status_interval_s", 0.0)
    return ServingWorker(_FakeEngine(), store, worker_id=wid, **kw)


# ---------------------------------------------------------------------------
# StoreQueue
# ---------------------------------------------------------------------------

class TestStoreQueue:
    def test_fifo_roundtrip_and_consumed_keys_deleted(self, store):
        w = StoreQueue(store, "q/t")
        r = StoreQueue(store, "q/t")
        for i in range(3):
            w.push({"i": i})
        assert [x["i"] for x in r.pop_all()] == [0, 1, 2]
        assert r.pop_all() == []
        # consumed item keys are deleted; only the cursors remain
        assert sorted(store.keys("q/t/")) == ["q/t/head", "q/t/tail"]

    def test_reader_waits_for_inflight_push(self, store):
        """Push is add-then-set: a reader racing between the two sees
        the tail but not the item — it must wait, not skip."""
        r = StoreQueue(store, "q/t")
        store.add("q/t/tail", 1)            # add landed, set hasn't
        assert r.pop_all() == []
        store.set("q/t/0", json.dumps({"i": 0}).encode())
        assert [x["i"] for x in r.pop_all()] == [0]
        assert r.holes == 0

    def test_permanent_hole_skipped_after_miss_limit(self, store):
        """A retried ``add`` whose first reply died may allocate a seq
        that is never written; the reader steps over it instead of
        wedging the queue forever."""
        w = StoreQueue(store, "q/t")
        store.add("q/t/tail", 1)            # seq 0: the hole
        w.push({"i": 1})                    # seq 1: real item
        r = StoreQueue(store, "q/t")
        got = []
        for _ in range(StoreQueue.MISS_LIMIT + 1):
            got += r.pop_all()
        assert [x["i"] for x in got] == [1]
        assert r.holes == 1

    def test_restarted_reader_catches_up_past_consumed(self, store):
        """A fresh reader (bounced process) starts at the smallest
        surviving key — it neither replays consumed items nor grinds
        through their deleted sequence numbers via the miss limit."""
        w = StoreQueue(store, "q/t")
        r1 = StoreQueue(store, "q/t")
        for i in range(5):
            w.push({"i": i})
        assert len(r1.pop_all()) == 5
        r2 = StoreQueue(store, "q/t")       # restart
        w.push({"i": 99})
        assert [x["i"] for x in r2.pop_all()] == [99]
        assert r2.holes == 0


# ---------------------------------------------------------------------------
# LeaseMonitor
# ---------------------------------------------------------------------------

class TestLeaseMonitor:
    def test_staleness_rules(self, store):
        clock = _Clock(100.0)
        mon = LeaseMonitor(store, prefix="cl/lease", deadline_s=5.0,
                           clock=clock)
        store.set("cl/lease/fresh",
                  json.dumps({"epoch": 1, "t": 99.0}).encode())
        store.set("cl/lease/old",
                  json.dumps({"epoch": 1, "t": 10.0}).encode())
        store.set("cl/lease/tomb", b"revoked:1")
        # missing == not yet monitored; old/tombstone == dead
        assert mon.stale_workers(["fresh", "old", "tomb", "absent"]) \
            == ["old", "tomb"]

    def test_monitor_is_a_heartbeat_monitor(self, store):
        """The dynamic-membership monitor reuses the PR-12 indexed one:
        same deadline semantics, same store, one implementation of the
        liveness rules."""
        mon = LeaseMonitor(store, deadline_s=3.0)
        assert isinstance(mon, serving.HeartbeatMonitor)
        assert mon.deadline_s == 3.0
        assert mon.interval_s == 1.0        # deadline / 3, inherited


# ---------------------------------------------------------------------------
# worker control plane (fakes: register / lease / commands)
# ---------------------------------------------------------------------------

class TestWorkerLease:
    def test_register_allocates_fresh_epochs(self, store):
        w = _fake_worker(store)
        e1 = w.register()
        e2 = w.register()
        assert e2 > e1
        rec = json.loads(store.get(f"cluster/workers/{w.worker_id}"))
        assert rec["state"] == "up" and rec["epoch"] == e2
        lease = json.loads(store.get(f"cluster/lease/{w.worker_id}"))
        assert lease["epoch"] == e2

    def test_renew_chains_and_tombstone_is_lease_lost(self, store):
        clock = _Clock()
        w = _fake_worker(store, clock=clock)
        w.register()
        clock.t += 1.0
        w.renew_lease()                     # CAS on our previous value
        lease = json.loads(store.get(f"cluster/lease/{w.worker_id}"))
        assert lease["t"] == clock.t
        # the controller revokes: the worker's chain is broken
        store.set(f"cluster/lease/{w.worker_id}", b"revoked:1")
        with pytest.raises(LeaseLost):
            w.renew_lease()

    def test_renew_retry_exhaustion_is_lease_lost(self, store):
        """A worker dark for longer than its retries cannot know whether
        it was revoked — exhaustion must be treated as a lost lease."""
        w = _fake_worker(store)
        w.register()
        rs.install_faults("cluster.lease@0x9:ConnectionError")
        with pytest.raises(LeaseLost):
            w.renew_lease()

    def test_register_transient_fault_is_retried(self, store):
        inj = rs.install_faults("cluster.register@0")
        w = _fake_worker(store)
        assert w.register() >= 1
        assert ("cluster.register", 0) in inj.fired

    def test_abort_epoch_reclaims_without_publishing(self, store):
        w = _fake_worker(store)
        w.register()

        class _St:
            finished = False
            slot = None

            class request:
                adapter = None
                request_id = "r1"
        w.engine._states["r1"] = _St()
        w._abort_epoch()
        assert w.engine._states == {}
        assert store.get("cluster/out/r1") is None


class TestCommandFencing:
    def _push_cmd(self, store, wid, cmd):
        StoreQueue(store, f"cluster/q/cmd/{wid}").push(cmd)

    def test_stale_epoch_command_rejected(self, store):
        w = _fake_worker(store)
        epoch = w.register()
        self._push_cmd(store, w.worker_id,
                       {"kind": "drain", "id": "c0", "epoch": epoch - 1})
        w.poll_commands()
        assert not w._stopping              # fenced, not applied
        assert w.stale_commands == 1
        ack = json.loads(store.get("cluster/cmdack/c0"))
        assert ack == {"ok": False, "reason": "stale_epoch",
                       "worker": w.worker_id}

    def test_command_fault_requeues_then_applies(self, store):
        """``cluster.command`` fires before the apply: the command is
        requeued for the next loop (idempotent per epoch), never lost
        and never half-applied."""
        w = _fake_worker(store)
        epoch = w.register()
        self._push_cmd(store, w.worker_id,
                       {"kind": "drain", "id": "c1", "epoch": epoch})
        inj = rs.install_faults("cluster.command@0")
        w.poll_commands()
        assert not w._stopping and len(w._pending_cmds) == 1
        assert ("cluster.command", 0) in inj.fired
        w.poll_commands()                   # fault plan spent: applies
        assert w._stopping
        rec = json.loads(store.get(f"cluster/workers/{w.worker_id}"))
        assert rec["state"] == "left"
        assert json.loads(store.get("cluster/cmdack/c1"))["ok"] is True

    def test_unknown_command_acked_not_fatal(self, store):
        w = _fake_worker(store)
        epoch = w.register()
        self._push_cmd(store, w.worker_id,
                       {"kind": "frobnicate", "id": "c2", "epoch": epoch})
        w.poll_commands()
        assert not w._stopping
        ack = json.loads(store.get("cluster/cmdack/c2"))
        assert ack["ok"] is False and "frobnicate" in ack["reason"]


# ---------------------------------------------------------------------------
# controller unit tests (records/statuses written directly — no engines)
# ---------------------------------------------------------------------------

def _seed_worker(store, wid, role, *, epoch=1, free_blocks=8,
                 queue_depth=0, lease_t=None, slo_breached=False,
                 status_t=None, **status_extra):
    store.set(f"cluster/workers/{wid}", json.dumps(
        {"worker": wid, "role": role, "epoch": epoch,
         "state": "up", "version": "v0"}).encode())
    store.set(f"cluster/status/{wid}", json.dumps(
        {"worker": wid, "role": role, "epoch": epoch,
         "t": time.time() if status_t is None else status_t,
         "queue_depth": queue_depth, "active": 0,
         "free_blocks": free_blocks, "num_blocks": 8,
         "slo_breached": slo_breached, **status_extra}).encode())
    if lease_t is not None:
        store.set(f"cluster/lease/{wid}", json.dumps(
            {"epoch": epoch, "t": lease_t}).encode())


class TestControllerRouting:
    def test_admission_routes_to_shallowest_prefill_queue(self, store):
        _seed_worker(store, "p0", "prefill", queue_depth=5)
        _seed_worker(store, "p1", "prefill", queue_depth=1)
        _seed_worker(store, "d0", "decode")
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        items = StoreQueue(store, "cluster/q/adm/p1").pop_all()
        assert [i["rid"] for i in items] == [rid]
        assert items[0]["epoch"] == 1
        assign = json.loads(store.get(f"cluster/assign/{rid}"))
        assert assign["wid"] == "p1"

    def test_handoff_ref_routes_to_most_free_decode(self, store):
        _seed_worker(store, "p0", "prefill")
        _seed_worker(store, "d0", "decode", free_blocks=2)
        _seed_worker(store, "d1", "decode", free_blocks=7)
        ctl = ClusterController(store)
        StoreQueue(store, "cluster/q/handoffs").push(
            {"rid": "r0", "xfer": "r0/p0/1", "nbytes": 64, "pages": 2,
             "prefilling": False, "adm": {"rid": "r0", "prompt": [1],
                                          "max_new_tokens": 2},
             "from": "p0"})
        ctl.pump()
        items = StoreQueue(store, "cluster/q/hoff/d1").pop_all()
        assert [i["rid"] for i in items] == ["r0"]

    def test_mid_prefill_snapshot_resumes_on_prefill_tier(self, store):
        _seed_worker(store, "p0", "prefill")
        _seed_worker(store, "d0", "decode")
        ctl = ClusterController(store)
        StoreQueue(store, "cluster/q/evac").push(
            {"rid": "r1", "xfer": "r1/p9/1", "nbytes": 64, "pages": 1,
             "prefilling": True, "adm": {"rid": "r1", "prompt": [1],
                                         "max_new_tokens": 2},
             "from": "p9"})
        ctl.pump()
        assert StoreQueue(store, "cluster/q/hoff/p0").pop_all() != []

    def test_unroutable_ref_pends_until_a_worker_joins(self, store):
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        assert ctl.pump()["pending"] == 1
        _seed_worker(store, "p0", "prefill")
        assert ctl.pump()["pending"] == 0
        assert [i["rid"] for i in
                StoreQueue(store, "cluster/q/adm/p0").pop_all()] == [rid]


class TestControllerFailureHandling:
    def test_stale_lease_reaped_and_assignments_rerouted(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        clock = _Clock(100.0)
        _seed_worker(store, "d0", "decode", lease_t=99.0)
        _seed_worker(store, "d1", "decode", lease_t=99.0)
        _seed_worker(store, "p0", "prefill", lease_t=99.0)
        ctl = ClusterController(store, lease_deadline_s=5.0, clock=clock)
        StoreQueue(store, "cluster/q/handoffs").push(
            {"rid": "r0", "xfer": "r0/p0/1", "nbytes": 64, "pages": 2,
             "prefilling": False, "adm": {"rid": "r0", "prompt": [1],
                                          "max_new_tokens": 2},
             "from": "p0"})
        ctl.pump()
        victim = json.loads(
            store.get("cluster/assign/r0").decode())["wid"]
        other = {"d0": "d1", "d1": "d0"}[victim]
        # the victim stops renewing; the others stay fresh
        clock.t = 110.0
        for w in ("p0", other):
            store.set(f"cluster/lease/{w}", json.dumps(
                {"epoch": 1, "t": clock.t}).encode())
        ctl.pump()
        rec = json.loads(store.get(f"cluster/workers/{victim}"))
        assert rec["state"] == "dead"
        assert store.get(f"cluster/lease/{victim}") \
            == f"revoked:1".encode()
        # the ref moved, token-identically (same xfer payload key)
        assign = json.loads(store.get("cluster/assign/r0"))
        assert assign["wid"] == other
        items = StoreQueue(store,
                           f"cluster/q/hoff/{other}").pop_all()
        assert [i["xfer"] for i in items] == ["r0/p0/1"]
        sink = obs.get_telemetry().sinks[0]
        assert [e["worker"] for e in sink.events("cluster_dead")] \
            == [victim]

    def test_stale_epoch_out_is_fenced(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "p0", "prefill", epoch=4)
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        # a zombie write from a previous epoch: dropped, key cleared
        store.set(f"cluster/out/{rid}", json.dumps(
            {"tokens": [1, 2], "reason": "eos", "worker": "p0",
             "epoch": 3}).encode())
        ctl.pump()
        assert rid not in ctl.outputs
        assert store.get(f"cluster/out/{rid}") is None
        sink = obs.get_telemetry().sinks[0]
        assert sink.events("cluster_stale_out")
        # the live epoch's write is collected
        store.set(f"cluster/out/{rid}", json.dumps(
            {"tokens": [1, 2, 3], "reason": "eos", "worker": "p0",
             "epoch": 4}).encode())
        ctl.pump()
        assert ctl.outputs[rid]["tokens"] == [1, 2, 3]


class TestAutoscale:
    def test_starved_prefill_tier_flips_idlest_decode(self, store):
        clock = _Clock(100.0)
        for wid, role, q in (("p0", "prefill", 10), ("p1", "prefill", 8),
                             ("d0", "decode", 2), ("d1", "decode", 0)):
            _seed_worker(store, wid, role, queue_depth=q)
        ctl = ClusterController(store, autoscale=True,
                                flip_queue_ratio=2.0, min_tier=1,
                                flip_cooldown_s=60.0, clock=clock)
        ctl.pump()
        items = StoreQueue(store, "cluster/q/cmd/d1").pop_all()
        assert [i["kind"] for i in items] == ["role_flip"]
        assert items[0]["role"] == "prefill"
        # cooldown: no second flip within the window
        ctl.pump()
        assert StoreQueue(store, "cluster/q/cmd/d1").pop_all() == []
        assert StoreQueue(store, "cluster/q/cmd/d0").pop_all() == []

    def test_slo_breach_flips_even_without_queue_imbalance(self, store):
        clock = _Clock(100.0)
        _seed_worker(store, "p0", "prefill", queue_depth=2,
                     slo_breached=True)
        _seed_worker(store, "d0", "decode")
        _seed_worker(store, "d1", "decode")
        ctl = ClusterController(store, autoscale=True,
                                flip_queue_ratio=100.0, min_tier=1,
                                clock=clock)
        ctl.pump()
        flips = (StoreQueue(store, "cluster/q/cmd/d0").pop_all()
                 + StoreQueue(store, "cluster/q/cmd/d1").pop_all())
        assert [i["kind"] for i in flips] == ["role_flip"]

    def test_min_tier_floor_blocks_flip(self, store):
        _seed_worker(store, "p0", "prefill", queue_depth=50)
        _seed_worker(store, "d0", "decode")
        ctl = ClusterController(store, autoscale=True,
                                flip_queue_ratio=2.0, min_tier=1)
        ctl.pump()
        assert StoreQueue(store, "cluster/q/cmd/d0").pop_all() == []


class TestControllerRecovery:
    def test_bounced_controller_rebuilds_assignments(self, store):
        _seed_worker(store, "p0", "prefill", epoch=2)
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        # a fresh controller over the same store sees the assignment
        # and collects the (correct-epoch) out
        ctl2 = ClusterController(store)
        store.set(f"cluster/out/{rid}", json.dumps(
            {"tokens": [7], "reason": "eos", "worker": "p0",
             "epoch": 2}).encode())
        ctl2.pump()
        assert ctl2.outputs[rid]["tokens"] == [7]


class TestTelemetryReport:
    def test_cluster_events_fold_into_table_and_json(self, tmp_path,
                                                     capsys):
        """tools/telemetry_report.py folds cluster_* events: membership
        churn, evacuations with requests moved, elasticity transitions
        with their wall ms, and the epoch-fence drop counts."""
        events = [
            {"event": "cluster_register", "worker": "w0", "epoch": 1},
            {"event": "cluster_register", "worker": "w0", "epoch": 2},
            {"event": "cluster_route", "id": "r0", "worker": "w0",
             "tier": "prefill", "xfer": False},
            {"event": "cluster_dead", "worker": "w1",
             "reason": "lease_expired"},
            {"event": "cluster_evacuate", "worker": "w1", "moved": 3,
             "by": "controller", "reason": "lease_expired"},
            {"event": "cluster_command", "worker": "w0", "id": "c0",
             "kind": "role_flip"},
            {"event": "cluster_role_flip", "worker": "w0",
             "role_from": "prefill", "role_to": "decode", "moved": 1,
             "ms": 12.5},
            {"event": "cluster_upgrade", "worker": "w0",
             "version": "v1", "moved": 0, "ms": 8.0},
            {"event": "cluster_lease_lost", "worker": "w1"},
            {"event": "cluster_autoscale", "worker": "w0"},
            {"event": "cluster_stale_command", "worker": "w1"},
            {"event": "cluster_stale_out", "id": "r9"},
            {"event": "cluster_transfer_failed", "id": "r3"},
            {"event": "cluster_deregister", "worker": "w0"},
        ]
        path = tmp_path / "cluster.jsonl"
        path.write_text(
            "\n".join(json.dumps(e) for e in events) + "\n")
        import sys
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(repo, "tools"))
        import telemetry_report as tr
        evs, malformed = tr.load_events([str(path)])
        cl = tr.summarize(evs)["cluster"]
        assert cl["registers"] == 2 and cl["deregisters"] == 1
        assert cl["deaths"] == 1
        assert cl["evacuations"] == 1 and cl["evacuated"] == 3
        assert cl["role_flips"] == 1 and cl["flip_ms"] == [12.5]
        assert cl["upgrades"] == 1 and cl["upgrade_ms"] == [8.0]
        assert cl["lease_losses"] == 1 and cl["autoscales"] == 1
        assert cl["transfer_failures"] == 1
        assert cl["commands"] == {"role_flip": 1}
        assert cl["stale"] == {"command": 1, "out": 1}
        text = tr.render(tr.summarize(evs), malformed)
        assert "Cluster control plane" in text
        assert "role flips, ms p50 / p95 | 1 , 12.5 / 12.5" in text
        assert "evacuations (requests moved) | 1 (3)" in text
        # the one-line JSON summary carries the same fold
        assert tr.main([str(path), "--json"]) == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["cluster"]["deaths"] == 1
        assert summary["cluster"]["flip_p95_ms"] == 12.5
        assert summary["cluster"]["evacuated_requests"] == 3
        assert summary["cluster"]["stale_drops"] == {"command": 1,
                                                     "out": 1}


# ---------------------------------------------------------------------------
# end-to-end with real engines (slow; the CI gate runs these cross-process)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(model, **kw)


@pytest.fixture(scope="module")
def reference(tiny_llama):
    eng = _engine(tiny_llama).warmup()
    rids = [eng.add_request(p, max_new_tokens=10) for p in PROMPTS]
    outs = eng.run()
    return [outs[r] for r in rids]


def _spin_up(model, store, roles, *, clock=None, **wkw):
    workers = []
    for i, role in enumerate(roles):
        eng = _engine(model, role=role).warmup()
        kw = dict(status_interval_s=0.0, steps_per_poll=1)
        if clock is not None:
            kw["clock"] = clock
        kw.update(wkw)
        w = ServingWorker(eng, store, worker_id=f"w{i}-{role}", **kw)
        w.register()
        w.publish_status()
        workers.append(w)
    return workers


def _drive(ctl, workers, rids, *, rounds=600, tick=None):
    for _ in range(rounds):
        for w in workers:
            if not w._stopping:
                w.step()
        ctl.pump()
        if tick is not None:
            tick()
        if all(r in ctl.outputs for r in rids):
            return
    raise AssertionError(
        f"undelivered: {[r for r in rids if r not in ctl.outputs]}")


def _blocks_clean(workers):
    for w in workers:
        alloc = w.engine.kv.allocator
        assert alloc.free_blocks == w.engine.kv.num_blocks, w.worker_id


@pytest.mark.slow
class TestClusterServing:
    def test_disagg_fleet_token_identity(self, tiny_llama, reference,
                                         store):
        """2 prefill + 2 decode workers over a real TCPStore serve the
        prompt mix greedy token-identical to the colocated engine, with
        every KV block reclaimed on every worker."""
        ctl = ClusterController(store, lease_deadline_s=100.0)
        workers = _spin_up(tiny_llama, store,
                           ("prefill", "prefill", "decode", "decode"))
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        _drive(ctl, workers, rids)
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        # handoffs actually crossed tiers (not all decoded locally)
        assert all(ctl.outputs[r]["worker"].endswith("decode")
                   for r in rids)
        _blocks_clean(workers)

    def test_kill_evacuation_token_identity(self, tiny_llama, reference,
                                            store):
        """A decode worker SIGKILLed mid-churn (modeled as: stops
        stepping, lease ages out): its requests re-route from the
        still-present transport payloads and finish token-identical on
        the survivors; the controller marks it dead."""
        clock = _Clock()
        ctl = ClusterController(store, lease_deadline_s=5.0,
                                clock=clock)
        workers = _spin_up(tiny_llama, store,
                           ("prefill", "prefill", "decode", "decode"),
                           clock=clock)
        victim = workers[2]
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        for _ in range(200):
            ctl.pump()
            for w in workers:
                w.step()
            clock.t += 0.1
            if any(not s.finished
                   for s in victim.engine._states.values()):
                break
        else:
            raise AssertionError("victim never got live work")
        survivors = [w for w in workers if w is not victim]
        _drive(ctl, survivors, rids,
               tick=lambda: setattr(clock, "t", clock.t + 0.5))
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        assert ctl.members()[victim.worker_id]["state"] == "dead"
        _blocks_clean(survivors)
        # the paused-then-resumed victim is fenced out of its epoch
        with pytest.raises(LeaseLost):
            victim.renew_lease()

    def test_sigterm_graceful_drain_completes_elsewhere(
            self, tiny_llama, reference, store):
        """Regression (worker graceful shutdown): SIGTERM enters the
        PreemptionGuard drain — in-flight KV hands off to the
        evacuation queue, every block is reclaimed, the lease
        deregisters, and the requests complete on other workers."""
        ctl = ClusterController(store, lease_deadline_s=100.0)
        workers = _spin_up(tiny_llama, store,
                           ("prefill", "decode", "decode"))
        victim = workers[1]
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        for _ in range(200):
            ctl.pump()
            for w in workers:
                w.step()
            if any(not s.finished
                   for s in victim.engine._states.values()):
                break
        else:
            raise AssertionError("victim never got live work")
        victim_rids = set(victim.engine._states)
        guard = PreemptionGuard()
        with guard:
            os.kill(os.getpid(), signal.SIGTERM)
            victim.run(guard=guard, sleep=lambda s: None)
        assert victim._stopping
        alloc = victim.engine.kv.allocator
        assert alloc.free_blocks == victim.engine.kv.num_blocks
        assert ctl.members()[victim.worker_id]["state"] == "left"
        survivors = [w for w in workers if w is not victim]
        _drive(ctl, survivors, rids)
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        assert victim_rids        # the drain actually moved live work
        for r in victim_rids & set(rids):
            assert ctl.outputs[r]["worker"] != victim.worker_id
        _blocks_clean(survivors)

    def test_role_flip_drain_ordering_and_zero_recompiles(
            self, tiny_llama, reference, store):
        """A forced prefill→decode flip mid-churn: the worker evacuates
        under its OLD role/epoch BEFORE re-registering under the new
        one (event order pinned), outputs stay token-identical, and the
        flip triggers zero recompiles — the compiled programs are
        role-independent."""
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        ctl = ClusterController(store, lease_deadline_s=100.0)
        workers = _spin_up(tiny_llama, store,
                           ("prefill", "prefill", "decode"))
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        for _ in range(3):
            ctl.pump()
            for w in workers:
                w.step()
        tel = obs.get_telemetry()
        c0 = tel.sentinel.compiles()
        flipped = workers[1]
        old_epoch = flipped.epoch
        cid = ctl.role_flip(flipped.worker_id, "decode")
        _drive(ctl, workers, rids)
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        assert tel.sentinel.compiles() == c0
        assert ctl.command_ack(cid)["ok"] is True
        assert flipped.role == "decode" \
            and flipped.engine.role == "decode"
        assert flipped.epoch > old_epoch
        sink = tel.sinks[0]
        evs = [e for e in sink.records
               if e.get("worker") == flipped.worker_id
               and e.get("event") in ("cluster_evacuate",
                                      "cluster_register")]
        flip_evac = [i for i, e in enumerate(evs)
                     if e["event"] == "cluster_evacuate"
                     and e.get("reason") == "role_flip"]
        re_reg = [i for i, e in enumerate(evs)
                  if e["event"] == "cluster_register"
                  and e.get("epoch") == flipped.epoch]
        assert flip_evac and re_reg and flip_evac[0] < re_reg[0]
        _blocks_clean(workers)

    def test_rolling_upgrade_token_identity(self, tiny_llama, reference,
                                            store):
        """drain → hot-swap params → rejoin under a new epoch, mid
        churn; the default param_source keeps the params so the upgrade
        is provably output-identical."""
        ctl = ClusterController(store, lease_deadline_s=100.0)
        workers = _spin_up(tiny_llama, store,
                           ("prefill", "decode", "decode"))
        upgraded = workers[2]
        old_epoch = upgraded.epoch
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        for _ in range(3):
            ctl.pump()
            for w in workers:
                w.step()
        cid = ctl.rolling_upgrade(upgraded.worker_id, "v1")
        _drive(ctl, workers, rids)
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        assert ctl.command_ack(cid)["ok"] is True
        assert upgraded.version == "v1"
        assert upgraded.epoch > old_epoch
        rec = ctl.members()[upgraded.worker_id]
        assert rec["version"] == "v1" and rec["state"] == "up"
        _blocks_clean(workers)

    def test_lease_lost_worker_rejoins_fresh(self, tiny_llama,
                                             reference, store):
        """A paused worker whose lease was revoked aborts its epoch
        (nothing published), rejoins fresh, and serves again — the
        run-loop recovery path."""
        clock = _Clock()
        ctl = ClusterController(store, lease_deadline_s=5.0,
                                clock=clock)
        workers = _spin_up(tiny_llama, store, ("prefill", "decode"),
                           clock=clock)
        paused = workers[1]
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS]
        for _ in range(200):
            ctl.pump()
            for w in workers:
                w.step()
            clock.t += 0.1
            if any(not s.finished
                   for s in paused.engine._states.values()):
                break
        else:
            raise AssertionError("never got live work")
        old_epoch = paused.epoch
        # the pause: only the prefill worker keeps renewing
        for _ in range(30):
            workers[0].step()
            ctl.pump()
            clock.t += 0.5
            if ctl.members()[paused.worker_id]["state"] == "dead":
                break
        # resume: the worker's next step loses the lease; mirror the
        # run()-loop recovery (abort + re-register) and keep serving
        with pytest.raises(LeaseLost):
            for _ in range(20):
                paused.step()
                clock.t += 0.5
        paused._abort_epoch()
        alloc = paused.engine.kv.allocator
        assert alloc.free_blocks == paused.engine.kv.num_blocks
        paused.register()
        assert paused.epoch > old_epoch
        paused.publish_status()
        _drive(ctl, workers, rids,
               tick=lambda: setattr(clock, "t", clock.t + 0.1))
        assert [ctl.outputs[r]["tokens"] for r in rids] == reference
        # every collected out is from a live epoch (fence held)
        for r in rids:
            if ctl.outputs[r]["worker"] == paused.worker_id:
                assert ctl.outputs[r]["epoch"] == paused.epoch


# ---------------------------------------------------------------------------
# fleet observability plane (docs/OBSERVABILITY.md "Fleet observability")
# ---------------------------------------------------------------------------

_PROM_SAMPLE_RE = __import__("re").compile(
    r"^[A-Za-z_:][A-Za-z0-9_:]*(\{[^{}]*\})? \S+$")


class TestStatusHardening:
    def test_unparsable_status_demotes_from_routing(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "p0", "prefill", queue_depth=5)
        _seed_worker(store, "p1", "prefill", queue_depth=0)
        store.set("cluster/status/p1", b"\x80 not json")
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        # p1 would win on queue depth; the garbage snapshot demotes it
        assert "p1" in ctl._status_demoted
        assert ctl._routable("prefill") == ["p0"]
        assert json.loads(
            store.get(f"cluster/assign/{rid}"))["wid"] == "p0"
        sink = obs.get_telemetry().sinks[0]
        assert [(e["worker"], e["reason"])
                for e in sink.events("cluster_status_demoted")] \
            == [("p1", "unparsable")]
        assert obs.get_registry().get(
            "cluster.status_demotions").snapshot() == 1

    def test_stale_status_demotes_and_recovers(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        clock = _Clock(1000.0)
        _seed_worker(store, "d0", "decode", status_t=999.0)
        _seed_worker(store, "d1", "decode", status_t=990.0)  # frozen
        ctl = ClusterController(store, clock=clock, status_stale_s=5.0)
        ctl.pump()
        assert ctl._routable("decode") == ["d0"]
        # demotion narrows ROUTING only — the lease monitor still owns
        # death, so the member record stays "up"
        assert ctl.members()["d1"]["state"] == "up"
        # a fresh snapshot rejoins routing, with a recovery event
        _seed_worker(store, "d1", "decode", status_t=1000.5)
        ctl.pump()
        assert sorted(ctl._routable("decode")) == ["d0", "d1"]
        sink = obs.get_telemetry().sinks[0]
        assert [e["worker"]
                for e in sink.events("cluster_status_recovered")] \
            == ["d1"]
        # one demotion transition, not one per pump
        assert obs.get_registry().get(
            "cluster.status_demotions").snapshot() == 1

    def test_fully_demoted_tier_falls_back_to_live(self, store):
        """Demotion must degrade routing, not wedge it: with every
        prefill worker demoted, admission falls back to the full live
        set rather than dropping the request."""
        clock = _Clock(1000.0)
        _seed_worker(store, "p0", "prefill", status_t=1.0)
        _seed_worker(store, "p1", "prefill", status_t=1.0)
        ctl = ClusterController(store, clock=clock, status_stale_s=5.0)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        assert ctl._routable("prefill") == []
        assert json.loads(
            store.get(f"cluster/assign/{rid}"))["wid"] in ("p0", "p1")

    def test_demotion_is_free_with_telemetry_disabled(self, store):
        """The hardening itself is NOT telemetry: demotion still
        protects routing with observability off (only the anomaly scan
        is gated)."""
        _seed_worker(store, "p0", "prefill", queue_depth=5)
        _seed_worker(store, "p1", "prefill", queue_depth=0)
        store.set("cluster/status/p1", b"garbage")
        ctl = ClusterController(store)
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        ctl.pump()
        assert json.loads(
            store.get(f"cluster/assign/{rid}"))["wid"] == "p0"


class TestFleetAnomalies:
    def test_straggler_convicted_after_consecutive_windows(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "d0", "decode", ttft_p95=10.0)
        _seed_worker(store, "d1", "decode", ttft_p95=12.0)
        _seed_worker(store, "d2", "decode", ttft_p95=100.0)
        ctl = ClusterController(store)
        ctl.pump()
        ctl.pump()
        assert ctl._stragglers == set()     # 2 windows: not yet
        ctl.pump()
        assert ctl._stragglers == {"d2"}
        sink = obs.get_telemetry().sinks[0]
        evs = sink.events("cluster_straggler")
        assert [(e["worker"], e["ttft_p95"]) for e in evs] \
            == [("d2", 100.0)]
        assert obs.get_registry().get(
            "cluster.stragglers").snapshot() == 1
        # a straggler counts as an SLO breach for the autoscaler
        assert ctl._tier_breached(["d0", "d1", "d2"])
        assert any(d["kind"] == "straggler" and d["worker"] == "d2"
                   for d in ctl.cluster_view()["decisions"])
        # back under the bar: unflag + recovery event
        _seed_worker(store, "d2", "decode", ttft_p95=11.0)
        ctl.pump()
        assert ctl._stragglers == set()
        assert [e["worker"] for e in
                sink.events("cluster_straggler_recovered")] == ["d2"]

    def test_two_worker_tier_uses_peer_median(self, store):
        """With 2 workers the median is the OTHER worker's value — a
        worker can never dodge conviction by dominating the sample."""
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "d0", "decode", step_p95=4.0)
        _seed_worker(store, "d1", "decode", step_p95=40.0)
        ctl = ClusterController(store)
        for _ in range(3):
            ctl.pump()
        assert ctl._stragglers == {"d1"}

    def test_recompile_escalation_alert(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "d0", "decode", compiles=3)
        ctl = ClusterController(store)
        ctl.pump()                          # first status = baseline
        sink = obs.get_telemetry().sinks[0]
        assert sink.events("cluster_recompile_alert") == []
        _seed_worker(store, "d0", "decode", compiles=5)
        ctl.pump()
        evs = sink.events("cluster_recompile_alert")
        assert [(e["worker"], e["compiles"], e["new"])
                for e in evs] == [("d0", 5, 2)]
        assert obs.get_registry().get(
            "cluster.recompile_alerts").snapshot() == 2
        ctl.pump()                          # no re-alert at 5
        assert len(sink.events("cluster_recompile_alert")) == 1

    def test_scan_gated_on_telemetry(self, store):
        _seed_worker(store, "d0", "decode", ttft_p95=10.0)
        _seed_worker(store, "d1", "decode", ttft_p95=900.0)
        ctl = ClusterController(store)
        for _ in range(5):
            ctl.pump()
        assert ctl._stragglers == set()


class TestWorkerTelemetryShipping:
    def test_publish_telemetry_ships_wire_snapshot(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        w = _fake_worker(store)
        w.register()
        obs.get_registry().histogram("serve.ttft_ms").observe(7.0)
        assert w.publish_telemetry()
        snap = json.loads(store.get("cluster/telemetry/w0").decode())
        assert snap["worker"] == "w0" and snap["role"] == "decode"
        assert snap["metrics"]["cluster.registers"] \
            == {"kind": "counter", "value": 1}
        assert snap["metrics"]["serve.ttft_ms"]["kind"] == "sketch"

    def test_publish_telemetry_disabled_is_inert(self, store):
        w = _fake_worker(store)
        w.register()
        assert w.publish_telemetry() is False
        assert store.get("cluster/telemetry/w0") is None
        assert w._publish_trace_segment("r0") is False
        assert store.keys("cluster/trace/") == []

    def test_sync_clock_estimates_offset(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        ctl = ClusterController(store, clock=_Clock(500.0))
        ctl.pump()                      # stamps cluster/clock
        w = _fake_worker(store, clock=_Clock(520.0))
        w.register()                    # register syncs
        assert w.clock_offset == pytest.approx(20.0)
        # skew rides every status so the stitcher can read it back
        w.publish_status()
        st = json.loads(store.get("cluster/status/w0").decode())
        assert st["clock_offset"] == pytest.approx(20.0)

    def test_sync_clock_without_tracer_is_inert(self, store):
        ctl = ClusterController(store, clock=_Clock(500.0))
        ctl.pump()
        assert store.get("cluster/clock") is None  # controller gated too
        w = _fake_worker(store, clock=_Clock(520.0))
        w.register()
        assert w.clock_offset == 0.0

    def test_exit_report_carries_mergeable_snapshot(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        w = _fake_worker(store)
        w.register()
        rep = w.report(compiles_baseline=0)
        assert rep["telemetry"]["cluster.registers"]["value"] == 1

    def test_exit_report_telemetry_none_when_disabled(self, store):
        w = _fake_worker(store)
        w.register()
        assert w.report(compiles_baseline=0)["telemetry"] is None


class TestControllerSurface:
    def _segment(self, rid, worker, role, t0, **summary):
        wall = round(sum(summary.values()), 3)
        return {"id": rid, "worker": worker, "role": role, "epoch": 1,
                "clock_offset": 0.0, "t0": t0, "events": [],
                "summary": {"queue_ms": 0.0, "prefill_ms": 0.0,
                            "xfer_ms": 0.0, "decode_ms": 0.0,
                            "wall_ms": wall, "decode_tokens": 0,
                            **summary}}

    def test_metrics_text_folds_worker_snapshots(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        w = _fake_worker(store)
        w.register()
        obs.get_registry().histogram("serve.ttft_ms").observe(7.0)
        w.publish_telemetry()
        ctl = ClusterController(store)
        text = ctl.metrics_text()
        for ln in text.splitlines():
            if ln and not ln.startswith("# "):
                assert _PROM_SAMPLE_RE.match(ln), ln
        assert 'serve_ttft_ms{worker="w0",role="decode",' in text
        assert "serve_ttft_ms_count" in text
        assert "\ncluster_live_workers 1" in text

    def test_http_surface(self, store):
        import http.client
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "d0", "decode", lease_t=time.time())
        for seg in (self._segment("r7", "wA", "prefill", 100.0,
                                  prefill_ms=8.0),
                    self._segment("r7", "wB", "decode", 100.020,
                                  decode_ms=30.0, decode_tokens=6)):
            store.set(f"cluster/trace/r7/{seg['worker']}:1:1",
                      json.dumps(seg).encode())
        ctl = ClusterController(store)
        ctl.pump()
        host, port = ctl.serve_http()
        try:
            def get(path):
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=10)
                conn.request("GET", path)
                r = conn.getresponse()
                body = r.read().decode()
                conn.close()
                return r.status, r.getheader("Content-Type"), body

            code, ctype, body = get("/healthz")
            assert (code, body) == (200, "ok\n")
            code, ctype, body = get("/metrics")
            assert code == 200
            assert ctype == "text/plain; version=0.0.4"
            assert "cluster_live_workers 1" in body
            code, ctype, body = get("/v1/cluster")
            assert code == 200 and ctype == "application/json"
            view = json.loads(body)
            assert view["workers"]["d0"]["lease_age_s"] is not None
            assert view["workers"]["d0"]["status_demoted"] is False
            # the stitched cross-host timeline, straight off the store
            code, ctype, body = get("/v1/requests/r7")
            assert code == 200
            tl = json.loads(body)
            assert tl["hosts"] == ["wA", "wB"]
            assert tl["xfer_ms"] == pytest.approx(12.0, abs=0.01)
            assert tl["monotonic"]
            code, _, body = get("/v1/requests/nope")
            assert code == 404 and json.loads(body)["id"] == "nope"
            code, _, _ = get("/v1/bogus")
            assert code == 404
            # idempotent: a second serve_http returns the same bind
            assert ctl.serve_http() == (host, port)
        finally:
            ctl.close_http()

    def test_trace_gc_bounds_store_keys(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, trace_retention=2)
        rids = []
        for i in range(4):
            rid = ctl.submit(PROMPTS[0], max_new_tokens=2)
            rids.append(rid)
            ctl.pump()
            store.set(f"cluster/trace/{rid}/p0:1:1", json.dumps(
                self._segment(rid, "p0", "prefill", 100.0 + i,
                              prefill_ms=1.0)).encode())
            store.set(f"cluster/out/{rid}", json.dumps(
                {"tokens": [1], "reason": "eos", "worker": "p0",
                 "epoch": 1}).encode())
            ctl.pump()
        assert all(r in ctl.outputs for r in rids)
        # only the newest `trace_retention` requests keep segments
        assert ctl.trace_segments(rids[0]) == []
        assert ctl.trace_segments(rids[1]) == []
        assert len(ctl.trace_segments(rids[2])) == 1
        assert len(ctl.trace_segments(rids[3])) == 1


@pytest.mark.slow
class TestFleetTracingEndToEnd:
    def test_cross_host_request_stitches_into_one_timeline(
            self, tiny_llama, store):
        """Real engines, real clocks (segment t0s are wall time —
        fake clocks would corrupt the corrected ordering): a request
        prefilled on w0 and decoded on w1 yields ONE stitched timeline
        with both hosts, a positive xfer phase, skew-corrected
        monotone segments, and the exact-sum invariant intact on every
        segment."""
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        ctl = ClusterController(store, lease_deadline_s=100.0)
        ctl.pump()                       # publish the controller clock
        workers = _spin_up(tiny_llama, store, ("prefill", "decode"))
        # registration read a clock stamp as stale as the engine
        # warmups are long; steady state re-syncs at every lease
        # renewal against the per-pump re-stamp — emulate one cycle
        ctl.pump()
        for w in workers:
            w._sync_clock()
        rids = [ctl.submit(p, max_new_tokens=10) for p in PROMPTS[:2]]
        _drive(ctl, workers, rids)
        for rid in rids:
            segs = ctl.trace_segments(rid)
            assert [s["worker"] for s in segs] \
                == ["w0-prefill", "w1-decode"]
            tl = ctl.request_timeline(rid)
            assert tl["hosts"] == ["w0-prefill", "w1-decode"]
            assert tl["monotonic"], tl
            assert tl["xfer_ms"] > 0
            assert tl["decode_tokens"] == 10
            for seg in tl["segments"]:
                s = seg["summary"]
                parts = sum(s[k] for k in ("queue_ms", "prefill_ms",
                                           "xfer_ms", "decode_ms"))
                assert abs(parts - s["wall_ms"]) <= 0.005
            # top-level accounting re-sums to the stitched wall
            assert tl["wall_ms"] == pytest.approx(
                tl["queue_ms"] + tl["prefill_ms"] + tl["xfer_ms"]
                + tl["decode_ms"], abs=1e-6)
        # the scrapeable surface saw the same fleet: per-worker rows
        # from shipped snapshots, tokens from merged counters
        text = ctl.metrics_text()
        assert 'worker="w0-prefill"' in text
        assert 'worker="w1-decode"' in text
        fleet = ctl.fleet_registry()
        assert fleet.get("serve.tokens").snapshot() >= 20
        _blocks_clean(workers)

# ---------------------------------------------------------------------------
# durable admission journal + controller failover (docs/SERVING.md
# "Cluster serving" failure matrix: controller-death rows)
# ---------------------------------------------------------------------------

def _retry():
    return RetryPolicy(max_attempts=3, backoff_s=0.0)


class TestControllerLease:
    def test_acquire_renew_release_chain(self, store):
        clock = _Clock(100.0)
        lease = ControllerLease(store, holder="ctlA", deadline_s=6.0,
                                clock=clock)
        assert lease.stale()                # absent == up for grabs
        assert lease.acquire() == 1
        rec = lease.observe()
        assert rec["holder"] == "ctlA" and rec["epoch"] == 1
        clock.t += 3.0                      # past interval (deadline/3)
        lease.renew()
        assert lease.observe()["t"] == 103.0
        lease.release()
        assert lease.observe() == {}        # tombstone: unparsable
        assert lease.stale()                # a standby takes over now

    def test_fresh_lease_blocks_second_acquire(self, store):
        clock = _Clock(100.0)
        ControllerLease(store, holder="ctlA", deadline_s=6.0,
                        clock=clock).acquire()
        standby = ControllerLease(store, holder="ctlB", deadline_s=6.0,
                                  clock=clock)
        with pytest.raises(LeaseLost):
            standby.acquire()

    def test_stale_takeover_bumps_epoch_and_fences_old_holder(
            self, store):
        clock = _Clock(100.0)
        old = ControllerLease(store, holder="ctlA", deadline_s=6.0,
                              clock=clock)
        assert old.acquire() == 1
        clock.t += 10.0                     # ctlA went dark
        standby = ControllerLease(store, holder="ctlB", deadline_s=6.0,
                                  clock=clock)
        assert standby.stale()
        assert standby.acquire() == 2       # counter, never reused
        # the zombie's chain is broken: its next renew is LeaseLost
        with pytest.raises(LeaseLost):
            old.renew(force=True)

    def test_epoch_counter_shared_with_leaseless_controllers(self, store):
        """One ``ctl/epoch`` counter serves lease acquisitions AND
        bare controller construction, so ``creq-<ctl>-<seq>`` rids can
        never collide between any two controller incarnations."""
        ctl = ClusterController(store)
        assert ctl.ctl_epoch == 1
        lease = ControllerLease(store, holder="ctlB", deadline_s=6.0)
        assert lease.acquire() == 2


class TestAdmissionJournal:
    def test_submit_is_durable_before_visible(self, store):
        """No workers yet: the admission is journaled and the
        unroutable ref mirrored to ``pend/`` before submit returns —
        a controller dying the instant after return loses nothing."""
        ctl = ClusterController(store, retry=_retry())
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        entry = json.loads(store.get(f"cluster/journal/{rid}"))
        assert entry["adm"]["prompt"] == [int(t) for t in PROMPTS[0]]
        assert entry["ctl"] == ctl.ctl_epoch and not entry.get("done")
        assert store.get(f"cluster/pend/{rid}") is not None
        assert ctl.pump()["pending"] == 1

    def test_rid_salted_with_ctl_epoch_across_bounce(self, store):
        """Regression: ``_rid_seq`` restarts at 0 on a controller
        bounce — without the epoch salt, the new controller's first
        rid collides with the old ``assign/``/``out/`` records."""
        _seed_worker(store, "p0", "prefill")
        ctl1 = ClusterController(store, retry=_retry())
        rid1 = ctl1.submit(PROMPTS[0], max_new_tokens=4)
        ctl1.pump()
        ctl2 = ClusterController(store, retry=_retry())   # the bounce
        rid2 = ctl2.submit(PROMPTS[1], max_new_tokens=4)
        assert rid1 != rid2
        assert rid1 == f"creq-{ctl1.ctl_epoch}-0"
        assert rid2 == f"creq-{ctl2.ctl_epoch}-0"
        assert ctl2.ctl_epoch > ctl1.ctl_epoch
        # rid1's recovered assignment survived untouched
        assert json.loads(
            store.get(f"cluster/assign/{rid1}"))["wid"] == "p0"

    def test_idempotency_key_dedupes_within_and_across_controllers(
            self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry())
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4,
                         idempotency_key="k-1")
        assert ctl.submit(PROMPTS[0], max_new_tokens=4,
                          idempotency_key="k-1") == rid
        sink = obs.get_telemetry().sinks[0]
        assert [e["id"] for e in sink.events("cluster_journal_dup")] \
            == [rid]
        # a bounced controller answers the same key from the store index
        ctl2 = ClusterController(store, retry=_retry())
        assert ctl2.submit(PROMPTS[0], max_new_tokens=4,
                           idempotency_key="k-1") == rid
        # exactly one admission was ever journaled
        assert store.keys("cluster/journal/") \
            == [f"cluster/journal/{rid}"]

    def test_journal_fault_retried_then_exhaustion_rejects_typed(
            self, store):
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry())
        inj = rs.install_faults("cluster.journal@0")
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4)
        assert ("cluster.journal", 0) in inj.fired
        assert store.get(f"cluster/journal/{rid}") is not None
        # exhaustion: the submission is rejected to the caller and
        # NOTHING was journaled — no half-admitted request
        rs.install_faults("cluster.journal@0x9")
        with pytest.raises(rs.InjectedFault):
            ctl.submit(PROMPTS[1], max_new_tokens=4,
                       idempotency_key="k-lost")
        assert store.get("cluster/jkey/k-lost") is None
        assert store.keys("cluster/journal/") \
            == [f"cluster/journal/{rid}"]

    def test_crash_at_submit_returned_not_yet_assigned_window(
            self, store):
        """The acceptance regression: a journaled submit whose
        controller dies before routing (journal entry, no ``assign/``,
        no ``pend/``) is re-routed by the next controller's recovery."""
        ctlA = ClusterController(store, retry=_retry())
        adm = {"rid": "creq-9-0", "prompt": [1, 2, 3],
               "max_new_tokens": 2, "temperature": 0.0,
               "eos_token_id": None, "tenant": None, "adapter": None,
               "key": None}
        # the exact window, frozen: the journal write landed, the
        # crash hit before _route could run
        assert ctlA._journal("creq-9-0", adm, None) == "creq-9-0"
        assert store.get("cluster/assign/creq-9-0") is None
        # ...and a second admission that pended (no eligible worker)
        rid2 = ctlA.submit(PROMPTS[0], max_new_tokens=4)
        del ctlA                            # the crash
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        _seed_worker(store, "p0", "prefill")
        ctlB = ClusterController(store, retry=_retry())
        ctlB.pump()
        for rid in ("creq-9-0", rid2):
            assert json.loads(
                store.get(f"cluster/assign/{rid}"))["wid"] == "p0"
        items = StoreQueue(store, "cluster/q/adm/p0").pop_all()
        assert sorted(i["rid"] for i in items) \
            == sorted(["creq-9-0", rid2])
        sink = obs.get_telemetry().sinks[0]
        replays = sink.events("cluster_journal_replay")
        # both live entries replay from the journal scan (the pend/
        # mirror of rid2 is then recognised as already pending)
        assert replays and replays[0]["replayed"] == 2
        assert replays[0]["pended"] == 0

    def test_follower_takeover_replays_journal_and_resumes(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        clock = _Clock(100.0)
        _seed_worker(store, "p0", "prefill", lease_t=99.0)
        active = ClusterController(
            store, clock=clock, retry=_retry(),
            lease=ControllerLease(store, holder="ctlA", deadline_s=5.0,
                                  clock=clock))
        rid = active.submit(PROMPTS[0], max_new_tokens=4,
                            idempotency_key="k-t")
        active.pump()
        standby = ClusterController(
            store, clock=clock, retry=_retry(), follower=True,
            lease=ControllerLease(store, holder="ctlB", deadline_s=5.0,
                                  clock=clock))
        assert standby.pump()["follower"] == 1      # lease still fresh
        assert standby.follower
        with pytest.raises(LeaseLost):              # cannot admit yet
            standby.submit(PROMPTS[1])
        # ctlA is SIGKILLed: it stops renewing; its lease ages out
        clock.t += 10.0
        store.set("cluster/lease/p0", json.dumps(
            {"epoch": 1, "t": clock.t}).encode())   # worker stays live
        res = standby.pump()                        # the takeover
        assert "follower" not in res
        assert not standby.follower
        assert standby.ctl_epoch > active.ctl_epoch
        assert rid in standby._assigned             # rebuilt from assign/
        assert standby._jkeys["k-t"] == rid         # index rebuilt
        sink = obs.get_telemetry().sinks[0]
        assert [e["ctl"] for e in sink.events("cluster_takeover")] \
            == [standby.ctl_epoch]
        # the worker's fenced output lands on the NEW controller
        store.set(f"cluster/out/{rid}", json.dumps(
            {"tokens": [4, 2], "reason": "eos", "worker": "p0",
             "epoch": 1}).encode())
        standby.pump()
        assert standby.outputs[rid]["tokens"] == [4, 2]
        # duplicate key against the standby: same rid, no re-admission
        assert standby.submit(PROMPTS[0], idempotency_key="k-t") == rid
        # the zombie is fenced the moment it wakes up
        with pytest.raises(LeaseLost):
            active.pump()
        assert sink.events("cluster_fenced")

    def test_takeover_fault_aborts_cleanly_and_retries(self, store):
        clock = _Clock(100.0)
        ControllerLease(store, holder="ctlA", deadline_s=5.0,
                        clock=clock).acquire()
        standby = ClusterController(
            store, clock=clock, retry=_retry(), follower=True,
            lease=ControllerLease(store, holder="ctlB", deadline_s=5.0,
                                  clock=clock))
        clock.t += 10.0
        inj = rs.install_faults("cluster.takeover@0")
        assert standby.pump()["follower"] == 1      # aborted, still
        assert standby.follower                     # a follower
        assert ("cluster.takeover", 0) in inj.fired
        standby.pump()                              # plan spent: wins
        assert not standby.follower
        assert standby.ctl_epoch == 2

    def test_tombstone_answers_dup_after_bounce(self, store):
        """Retirement keeps the finished tokens in the journal
        tombstone, so a bounced controller (whose ``out/`` keys were
        consumed) still answers a duplicate key with the output."""
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry())
        rid = ctl.submit(PROMPTS[0], max_new_tokens=4,
                         idempotency_key="k-d")
        ctl.pump()
        store.set(f"cluster/out/{rid}", json.dumps(
            {"tokens": [7, 8], "reason": "eos", "worker": "p0",
             "epoch": 1}).encode())
        ctl.pump()
        assert store.get(f"cluster/out/{rid}") is None  # consumed
        tomb = json.loads(store.get(f"cluster/journal/{rid}"))
        assert tomb["done"] and tomb["tokens"] == [7, 8]
        ctl2 = ClusterController(store, retry=_retry())
        assert ctl2.submit(PROMPTS[0], idempotency_key="k-d") == rid
        assert ctl2.outputs[rid]["tokens"] == [7, 8]

    def test_journal_gc_bounds_store_keys_under_churn(self, store):
        """Sustained churn with a small retention: journal, assign and
        jkey key counts PLATEAU instead of growing without bound."""
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry(),
                                journal_retention=2)
        sizes = []
        for i in range(6):
            rid = ctl.submit(PROMPTS[0], max_new_tokens=2,
                             idempotency_key=f"k-{i}")
            ctl.pump()
            store.set(f"cluster/out/{rid}", json.dumps(
                {"tokens": [i], "reason": "eos", "worker": "p0",
                 "epoch": 1}).encode())
            ctl.pump()
            sizes.append((len(store.keys("cluster/journal/")),
                          len(store.keys("cluster/assign/")),
                          len(store.keys("cluster/jkey/"))))
        assert sizes[-1] == (2, 2, 2)
        assert sizes[-1] == sizes[-2] == sizes[-3]      # the plateau
        # the newest entries are the survivors
        kept = store.keys("cluster/journal/")
        assert all(json.loads(store.get(k))["done"] for k in kept)


class TestWorkerCtlFencing:
    def test_command_below_ctl_watermark_is_fenced(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        w = _fake_worker(store)
        epoch = w.register()
        q = StoreQueue(store, f"cluster/q/cmd/{w.worker_id}")
        # a command from controller epoch 2 raises the watermark...
        q.push({"kind": "frobnicate", "id": "cA", "epoch": epoch,
                "ctl": 2})
        w.poll_commands()
        assert w._ctl_seen == 2
        # ...so the SIGKILLed controller's late command (ctl 1) is
        # fenced: acked typed, never applied
        q.push({"kind": "drain", "id": "cB", "epoch": epoch, "ctl": 1})
        w.poll_commands()
        assert not w._stopping
        ack = json.loads(store.get("cluster/cmdack/cB"))
        assert ack == {"ok": False, "reason": "stale_ctl",
                       "worker": w.worker_id}

    def test_stale_ctl_queue_item_dropped(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        w = _fake_worker(store)
        epoch = w.register()
        w._ctl_seen = 5                     # saw controller epoch 5
        StoreQueue(store, f"cluster/q/adm/{w.worker_id}").push(
            {"rid": "r0", "adm": {"rid": "r0", "prompt": [1],
                                  "max_new_tokens": 2},
             "wid": w.worker_id, "epoch": epoch, "ctl": 3})
        w.poll_intake()                     # dropped before the engine
        assert w.engine._states == {}
        sink = obs.get_telemetry().sinks[0]
        assert [(e["id"], e["ctl"], e["ctl_seen"])
                for e in sink.events("cluster_stale_item")] \
            == [("r0", 3, 5)]

    def test_unstamped_items_pass(self, store):
        """Items without a ``ctl`` stamp (pre-journal controllers,
        direct test pushes) are never fenced."""
        w = _fake_worker(store)
        epoch = w.register()
        w._ctl_seen = 5
        q = StoreQueue(store, f"cluster/q/cmd/{w.worker_id}")
        q.push({"kind": "frobnicate", "id": "cC", "epoch": epoch})
        w.poll_commands()
        ack = json.loads(store.get("cluster/cmdack/cC"))
        assert "frobnicate" in ack["reason"]    # reached the apply


class _StubSpawner:
    def __init__(self):
        self.spawned = []

    def spawn(self, role):
        wid = f"spawn-{role}-{len(self.spawned)}"
        self.spawned.append((role, wid))
        return wid


class TestSpawnerAutoscale:
    def _fleet_at_floor(self, store, *, breached=True):
        _seed_worker(store, "p0", "prefill", queue_depth=2,
                     slo_breached=breached)
        _seed_worker(store, "d0", "decode")

    def test_persistent_breach_at_flip_floor_spawns(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        clock = _Clock(100.0)
        self._fleet_at_floor(store)
        sp = _StubSpawner()
        ctl = ClusterController(store, autoscale=True, min_tier=1,
                                flip_queue_ratio=100.0,
                                flip_cooldown_s=0.0, clock=clock,
                                spawner=sp, spawn_breach_windows=3)
        ctl.pump()
        ctl.pump()
        assert sp.spawned == []             # breach must PERSIST
        ctl.pump()
        assert [r for r, _ in sp.spawned] == ["prefill"]
        sink = obs.get_telemetry().sinks[0]
        assert [e["role"] for e in sink.events("cluster_spawn")] \
            == ["prefill"]
        assert obs.get_registry().get("cluster.spawns").snapshot() == 1
        assert any(d["kind"] == "spawn"
                   for d in ctl.cluster_view()["decisions"])

    def test_max_workers_caps_spawn(self, store):
        self._fleet_at_floor(store)
        sp = _StubSpawner()
        ctl = ClusterController(store, autoscale=True, min_tier=1,
                                flip_queue_ratio=100.0,
                                flip_cooldown_s=0.0, spawner=sp,
                                spawn_breach_windows=1, max_workers=2)
        for _ in range(4):
            ctl.pump()
        assert sp.spawned == []             # 2 live == the cap

    def test_idle_fleet_drains_emptiest_of_larger_tier(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        clock = _Clock(100.0)
        _seed_worker(store, "p0", "prefill")
        _seed_worker(store, "d0", "decode")
        _seed_worker(store, "d1", "decode")
        ctl = ClusterController(store, autoscale=True, min_tier=1,
                                flip_cooldown_s=0.0, clock=clock,
                                spawner=_StubSpawner(),
                                scale_down_windows=2)
        ctl.pump()
        assert StoreQueue(store, "cluster/q/cmd/d0").pop_all() == []
        ctl.pump()                          # second idle window: drain
        items = StoreQueue(store, "cluster/q/cmd/d0").pop_all()
        assert [i["kind"] for i in items] == ["drain"]
        sink = obs.get_telemetry().sinks[0]
        assert [e["worker"] for e in sink.events("cluster_scale_down")] \
            == ["d0"]

    def test_subprocess_spawner_argv_and_reap(self, store, monkeypatch):
        """The default spawner launches ``python -m
        paddle_tpu.serving.worker`` with the store/role/factory wiring;
        reap() harvests exits without blocking."""
        import subprocess as sp_mod

        class _Proc:
            def __init__(self, cmd, env=None, cwd=None):
                self.cmd = cmd
                self._rc = None

            def poll(self):
                return self._rc

        launched = []

        def fake_popen(cmd, env=None, cwd=None):
            p = _Proc(cmd, env, cwd)
            launched.append(p)
            return p

        monkeypatch.setattr(sp_mod, "Popen", fake_popen)
        sp = WorkerSpawner("127.0.0.1:9", "mod:factory",
                           lease_deadline_s=3.0,
                           extra_args=("--seed", "7"))
        wid = sp.spawn("decode")
        assert wid.startswith("spawn-decode-")
        cmd = launched[0].cmd
        assert cmd[1:3] == ["-m", "paddle_tpu.serving.worker"]
        for flag, val in (("--store", "127.0.0.1:9"),
                          ("--role", "decode"),
                          ("--factory", "mod:factory"),
                          ("--worker-id", wid),
                          ("--lease-deadline-s", "3.0"),
                          ("--seed", "7")):
            assert val == cmd[cmd.index(flag) + 1] if flag != "--seed" \
                else val in cmd
        assert sp.reap() == {}              # still running
        launched[0]._rc = 0
        assert sp.reap() == {wid: 0}
        assert sp.procs == {}


# ---------------------------------------------------------------------------
# cluster gateway (serving/gateway.py)
# ---------------------------------------------------------------------------

class TestClusterGatewayPolicy:
    def _gw(self, store, **kw):
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry())
        return ClusterGateway(ctl, **kw)

    def test_admit_then_rate_limited_with_retry_hint(self, store):
        gw = self._gw(store, tenants={"free": TenantPolicy(
            rate_tokens_per_s=1.0, burst_tokens=5.0)})
        adm = gw.submit_request([1, 2], tenant="free", max_new_tokens=2)
        assert adm.admitted and adm.request_id
        shed = gw.submit_request([1, 2], tenant="free", max_new_tokens=2)
        assert (shed.admitted, shed.reason) == (False, "rate_limited")
        assert shed.retry_after_s > 0
        assert gw.shed_counts == {"rate_limited": 1}

    def test_quota_queue_full_and_slo_shed(self, store):
        gw = self._gw(store, max_live=2, slo_queue_depth=1,
                      tenants={"default": TenantPolicy(),
                               "small": TenantPolicy(max_live_requests=1),
                               "paid": TenantPolicy(priority=1)})
        assert gw.submit_request([1], tenant="small").admitted
        assert gw.submit_request(
            [1], tenant="small").reason == "quota"
        # backlog >= slo_queue_depth: default-tier (priority 0) sheds,
        # the paid tier rides over the floor
        assert gw.submit_request([1], tenant="default").reason \
            == "slo_shed"
        assert gw.submit_request([1], tenant="paid").admitted
        # the gateway-wide live cap is last
        assert gw.submit_request([1], tenant="paid").reason \
            == "queue_full"

    def test_gateway_fault_sheds_one_request_typed(self, store):
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        gw = self._gw(store)
        inj = rs.install_faults("serve.gateway@0")
        shed = gw.submit_request([1, 2, 3])
        assert (shed.admitted, shed.reason) == (False, "gateway_fault")
        assert ("serve.gateway", 0) in inj.fired
        assert gw.submit_request([1, 2, 3]).admitted  # gateway survives
        sink = obs.get_telemetry().sinks[0]
        assert [e["reason"] for e in sink.events("serve_gateway")
                if e.get("state") == "shed"] == ["gateway_fault"]

    def test_duplicate_key_bypasses_policy_sheds(self, store):
        gw = self._gw(store, max_live=1)
        adm = gw.submit_request([1], idempotency_key="k-g")
        assert adm.admitted
        dup = gw.submit_request([1], idempotency_key="k-g")
        assert dup.admitted and dup.request_id == adm.request_id
        assert dup.reason == "duplicate" and gw.dup_hits == 1
        assert gw.ctl.store.keys("cluster/journal/") \
            == [f"cluster/journal/{adm.request_id}"]

    def test_draining_sheds_typed(self, store):
        gw = self._gw(store)
        gw.begin_drain(reason="test")
        shed = gw.submit_request([1])
        assert (shed.admitted, shed.reason) == (False, "draining")
        assert shed.retry_after_s == gw.drain_retry_after_s

    def test_health_and_metrics_surface(self, store):
        gw = self._gw(store)
        gw.submit_request([1, 2])
        h = gw.health()
        assert h["status"] == "serving" and h["live_requests"] == 1
        assert h["ctl_epoch"] == gw.ctl.ctl_epoch
        text = gw.metrics_text()
        assert "gateway_live_requests 1" in text
        assert "gateway_draining 0" in text


class TestClusterGatewayHTTP:
    @pytest.fixture
    def gw(self, store):
        _seed_worker(store, "p0", "prefill")
        ctl = ClusterController(store, retry=_retry())
        gw = ClusterGateway(ctl, poll_s=0.002, output_timeout_s=20.0)
        gw.start()
        yield gw
        gw.close()

    def _post(self, gw, body, headers=None):
        import http.client
        host, port = gw.address
        conn = http.client.HTTPConnection(host, port, timeout=20)
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json",
                      **(headers or {})})
        r = conn.getresponse()
        out = (r.status, dict(r.getheaders()), r.read().decode())
        conn.close()
        return out

    def _complete(self, gw, key, tokens):
        """Worker stand-in: wait for the routed assignment of the
        journaled key, then publish its fenced output record."""
        store = gw.ctl.store
        for _ in range(2000):
            raw = store.get(f"cluster/jkey/{key}")
            if raw is not None:
                rid = raw.decode()
                a = store.get(f"cluster/assign/{rid}")
                if a is not None:
                    a = json.loads(a)
                    store.set(f"cluster/out/{rid}", json.dumps(
                        {"tokens": tokens, "reason": "eos",
                         "worker": a["wid"], "epoch": a["epoch"]}).encode())
                    return rid
            time.sleep(0.002)
        raise AssertionError(f"key {key!r} never routed")

    def test_post_sse_stream_and_idempotent_replay(self, gw):
        import threading
        done = threading.Thread(
            target=self._complete, args=(gw, "k-http", [5, 6, 7]))
        done.start()
        code, hdrs, body = self._post(
            gw, {"prompt": [1, 2, 3], "max_tokens": 4, "stream": True},
            {"Idempotency-Key": "k-http"})
        done.join()
        assert code == 200
        assert hdrs["Content-Type"] == "text/event-stream"
        datas = [ln[len("data: "):] for ln in body.splitlines()
                 if ln.startswith("data: ")]
        assert datas[-1] == "[DONE]"
        chunks = [json.loads(d) for d in datas[:-1]]
        assert [c["choices"][0]["token_id"] for c in chunks] == [5, 6, 7]
        assert chunks[-1]["choices"][0]["finish_reason"] == "eos"
        rid = chunks[0]["id"]
        # the duplicate POST: same rid, same stream, no new admission
        code2, _, body2 = self._post(
            gw, {"prompt": [1, 2, 3], "max_tokens": 4},
            {"Idempotency-Key": "k-http"})
        assert code2 == 200
        rep = json.loads(body2)
        assert rep["id"] == rid
        assert rep["choices"][0]["token_ids"] == [5, 6, 7]
        assert rep["usage"]["completion_tokens"] == 3
        assert gw.ctl.store.keys("cluster/journal/") \
            == [f"cluster/journal/{rid}"]
        assert gw.dup_hits == 1

    def test_drain_answers_typed_503_then_drains(self, gw):
        import threading
        done = threading.Thread(
            target=self._complete, args=(gw, "k-dr", [9]))
        done.start()
        code, _, body = self._post(
            gw, {"prompt": [1], "max_tokens": 2},
            {"Idempotency-Key": "k-dr"})
        done.join()
        assert code == 200
        gw.begin_drain(reason="test")
        code, hdrs, body = self._post(gw, {"prompt": [1]})
        assert code == 503
        err = json.loads(body)["error"]
        assert err["type"] == "draining"
        assert int(hdrs["Retry-After"]) >= 1
        assert gw.wait_drained(timeout=10.0)
        assert gw.health()["status"] == "draining"

    def test_malformed_body_is_400(self, gw):
        code, _, body = self._post(gw, {"max_tokens": 2})
        assert code == 400
        assert json.loads(body)["error"]["type"] == "invalid_request"

    def test_healthz_and_metrics_endpoints(self, gw):
        import http.client
        host, port = gw.address
        for path, marker in (("/healthz", '"status": "serving"'),
                             ("/metrics", "gateway_draining 0"),
                             ("/nope", "not_found")):
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.request("GET", path)
            r = conn.getresponse()
            assert marker in r.read().decode()
            conn.close()


class TestGatewayQueueCursor:
    def test_gateway_request_queue_cursor_survives_restart(self, store):
        """The gateway-facing submission queue (``gate/req``, consumed
        by the cross-process controller helper) persists its consumer
        cursor: a bounced consumer resumes exactly after the consumed
        prefix — no replay, no hole-grinding."""
        w = StoreQueue(store, "cluster/gate/req")
        r1 = StoreQueue(store, "cluster/gate/req")
        for i in range(3):
            w.push({"i": i})
        assert [x["i"] for x in r1.pop_all()] == [0, 1, 2]
        assert store.get("cluster/gate/req/head") == b"3"
        w.push({"i": 3})
        r2 = StoreQueue(store, "cluster/gate/req")    # the bounce
        assert [x["i"] for x in r2.pop_all()] == [3]
        assert r2.holes == 0
        assert store.get("cluster/gate/req/head") == b"4"
