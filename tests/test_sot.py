"""SOT-lite: automatic control-flow conversion under ``to_static``
(reference: python/paddle/jit/sot bytecode capture; here an AST rewrite —
see paddle_tpu/jit/sot.py).

Contract (VERDICT r2 #3): a function/model written with a bare
data-dependent ``if``/``while`` runs under to_static unmodified, matches
eager, and unconvertible patterns keep the graph-break diagnostic or the
eager fallback with a signature-keyed guard cache."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.jit import GraphBreakError, to_static
from paddle_tpu.jit.sot import convert_control_flow


class TestIfConversion:
    def test_if_else_assignment(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        g = to_static(f)
        pos = jnp.asarray([1.0, 2.0])
        neg = jnp.asarray([-3.0, 1.0])
        np.testing.assert_allclose(g(pos), f(pos))
        np.testing.assert_allclose(g(neg), f(neg))

    def test_if_without_else(self):
        def f(x):
            y = x + 1.0
            if y.mean() > 0:
                y = y * 10.0
            return y

        g = to_static(f)
        for v in ([1.0, 1.0], [-5.0, -5.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_elif_chain_returns(self):
        def f(x):
            s = x.sum()
            if s > 1.0:
                return x * 2.0
            elif s > -1.0:
                return x * 0.5
            else:
                return -x

        g = to_static(f)
        for v in ([5.0], [0.1], [-9.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_branches_actually_compiled_once(self):
        """The converted function traces ONCE and both branches live in the
        compiled program — no per-value retrace, no eager fallback."""
        traces = []

        def f(x):
            traces.append(1)
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        g = to_static(f)
        a = g(jnp.asarray([1.0]))
        b = g(jnp.asarray([-1.0]))
        np.testing.assert_allclose(a, [2.0])
        np.testing.assert_allclose(b, [-3.0])
        assert len(traces) == 1  # same shape -> one trace, value-dispatched

    def test_nested_if(self):
        def f(x):
            if x.sum() > 0:
                if x.max() > 10.0:
                    y = x * 100.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        g = to_static(f)
        for v in ([20.0], [1.0], [-4.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_concrete_pred_keeps_python_semantics(self):
        def f(x, flag):
            if flag:          # concrete python bool: only taken branch runs
                y = x + 1.0
            else:
                y = x.bad_attribute_that_would_raise  # must never execute
            return y

        g = to_static(f, static_argnums=(1,))
        np.testing.assert_allclose(g(jnp.asarray([1.0]), True), [2.0])


class TestWhileConversion:
    def test_while_tensor_pred(self):
        def f(x):
            while x.sum() < 100.0:
                x = x * 2.0
            return x

        g = to_static(f)
        x = jnp.asarray([1.0, 2.0])
        np.testing.assert_allclose(g(x), f(x))

    def test_while_multi_carry(self):
        def f(x):
            n = jnp.zeros((), jnp.int32)
            while x.sum() < 50.0:
                x = x + 1.0
                n = n + 1
            return x, n

        g = to_static(f)
        ex, en = f(jnp.asarray([0.0]))
        cx, cn = g(jnp.asarray([0.0]))
        np.testing.assert_allclose(cx, ex)
        assert int(cn) == int(en) == 50

    def test_while_concrete_pred_unrolls(self):
        def f(x):
            i = 0
            while i < 3:     # concrete: unrolls under trace
                x = x * 2.0
                i += 1
            return x

        g = to_static(f)
        np.testing.assert_allclose(g(jnp.asarray([1.0])), [8.0])


class TestFallback:
    def test_one_sided_assignment_full_graph_raises(self):
        def f(x):
            if x.sum() > 0:
                extra = x * 5.0
                return extra
            return x  # `extra` undefined on this path; value-form declined

        g = to_static(f, full_graph=True)
        with pytest.raises(GraphBreakError):
            g(jnp.asarray([1.0]))

    def test_unconvertible_falls_back_eagerly(self):
        seen = []

        def f(x):
            if x.sum() > 0:   # side-effect branch: not convertible
                seen.append(1)
            return x * 2.0

        g = to_static(f, full_graph=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = g(jnp.asarray([3.0]))
            np.testing.assert_allclose(out, [6.0])
            assert seen == [1]
            # guard cache: second call with same signature goes straight to
            # eager (side effect runs again; no exception, no re-jit)
            g(jnp.asarray([4.0]))
            assert seen == [1, 1]

    def test_attribute_store_branch_not_captured(self):
        """lax.cond traces BOTH branches; a branch mutating object state
        must keep graph-break behavior, not convert (else the mutation
        runs unconditionally and leaks tracers)."""
        class Box:
            hits = 0

        box = Box()

        def f(x):
            if x.sum() > 0:
                y = x * 2.0
                box.hits = box.hits + 1   # side effect: blocks conversion
            else:
                y = -x
            return y

        g = to_static(f, full_graph=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = g(jnp.asarray([-1.0]))   # negative: branch NOT taken
        np.testing.assert_allclose(out, [1.0])
        assert box.hits == 0               # eager fallback, branch skipped

    def test_conversion_off_restores_old_behavior(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = -x
            return y

        g = to_static(f, convert_control_flow=False, full_graph=True)
        with pytest.raises(GraphBreakError):
            g(jnp.asarray([1.0]))


class TestLayerConversion:
    def test_model_with_bare_if_runs_unmodified(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if h.mean() > 0:       # bare data-dependent branch
                    out = h * 2.0
                else:
                    out = h * 0.5
                return out

        pt.seed(0)
        m = Gate()
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 4)).astype("float32"))
        eager = m(x)
        g = to_static(m)
        np.testing.assert_allclose(np.asarray(g(x)), np.asarray(eager),
                                   rtol=1e-6)

    def test_model_with_while_decode_loop(self):
        class Doubler(nn.Layer):
            def forward(self, x):
                while x.sum() < 30.0:
                    x = x * 2.0
                return x

        m = Doubler()
        x = jnp.asarray([1.0, 1.5])
        g = to_static(m)
        np.testing.assert_allclose(np.asarray(g(x)), np.asarray(m(x)))


class TestConvertFunction:
    def test_no_control_flow_unchanged(self):
        def f(x):
            return x * 2

        _, changed = convert_control_flow(f)
        assert not changed

    def test_closure_snapshot(self):
        scale = jnp.asarray(3.0)

        def make():
            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x
                return y
            return f

        f = make()
        g = to_static(f)
        np.testing.assert_allclose(g(jnp.asarray([2.0])), [6.0])
