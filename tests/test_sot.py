"""SOT-lite: automatic control-flow conversion under ``to_static``
(reference: python/paddle/jit/sot bytecode capture; here an AST rewrite —
see paddle_tpu/jit/sot.py).

Contract (VERDICT r2 #3): a function/model written with a bare
data-dependent ``if``/``while`` runs under to_static unmodified, matches
eager, and unconvertible patterns keep the graph-break diagnostic or the
eager fallback with a signature-keyed guard cache."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.jit import GraphBreakError, to_static
from paddle_tpu.jit.sot import convert_control_flow


class TestIfConversion:
    def test_if_else_assignment(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        g = to_static(f)
        pos = jnp.asarray([1.0, 2.0])
        neg = jnp.asarray([-3.0, 1.0])
        np.testing.assert_allclose(g(pos), f(pos))
        np.testing.assert_allclose(g(neg), f(neg))

    def test_if_without_else(self):
        def f(x):
            y = x + 1.0
            if y.mean() > 0:
                y = y * 10.0
            return y

        g = to_static(f)
        for v in ([1.0, 1.0], [-5.0, -5.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_elif_chain_returns(self):
        def f(x):
            s = x.sum()
            if s > 1.0:
                return x * 2.0
            elif s > -1.0:
                return x * 0.5
            else:
                return -x

        g = to_static(f)
        for v in ([5.0], [0.1], [-9.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_branches_actually_compiled_once(self):
        """The converted function traces ONCE and both branches live in the
        compiled program — no per-value retrace, no eager fallback."""
        traces = []

        def f(x):
            traces.append(1)
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        g = to_static(f)
        a = g(jnp.asarray([1.0]))
        b = g(jnp.asarray([-1.0]))
        np.testing.assert_allclose(a, [2.0])
        np.testing.assert_allclose(b, [-3.0])
        assert len(traces) == 1  # same shape -> one trace, value-dispatched

    def test_nested_if(self):
        def f(x):
            if x.sum() > 0:
                if x.max() > 10.0:
                    y = x * 100.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        g = to_static(f)
        for v in ([20.0], [1.0], [-4.0]):
            x = jnp.asarray(v)
            np.testing.assert_allclose(g(x), f(x))

    def test_concrete_pred_keeps_python_semantics(self):
        def f(x, flag):
            if flag:          # concrete python bool: only taken branch runs
                y = x + 1.0
            else:
                y = x.bad_attribute_that_would_raise  # must never execute
            return y

        g = to_static(f, static_argnums=(1,))
        np.testing.assert_allclose(g(jnp.asarray([1.0]), True), [2.0])


class TestWhileConversion:
    def test_while_tensor_pred(self):
        def f(x):
            while x.sum() < 100.0:
                x = x * 2.0
            return x

        g = to_static(f)
        x = jnp.asarray([1.0, 2.0])
        np.testing.assert_allclose(g(x), f(x))

    def test_while_multi_carry(self):
        def f(x):
            n = jnp.zeros((), jnp.int32)
            while x.sum() < 50.0:
                x = x + 1.0
                n = n + 1
            return x, n

        g = to_static(f)
        ex, en = f(jnp.asarray([0.0]))
        cx, cn = g(jnp.asarray([0.0]))
        np.testing.assert_allclose(cx, ex)
        assert int(cn) == int(en) == 50

    def test_while_concrete_pred_unrolls(self):
        def f(x):
            i = 0
            while i < 3:     # concrete: unrolls under trace
                x = x * 2.0
                i += 1
            return x

        g = to_static(f)
        np.testing.assert_allclose(g(jnp.asarray([1.0])), [8.0])


class TestFallback:
    def test_one_sided_assignment_full_graph_raises(self):
        def f(x):
            if x.sum() > 0:
                extra = x * 5.0
                return extra
            return x  # `extra` undefined on this path; value-form declined

        g = to_static(f, full_graph=True)
        with pytest.raises(GraphBreakError):
            g(jnp.asarray([1.0]))

    def test_unconvertible_falls_back_eagerly(self):
        seen = []

        def f(x):
            if x.sum() > 0:   # side-effect branch: not convertible
                seen.append(1)
            return x * 2.0

        g = to_static(f, full_graph=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = g(jnp.asarray([3.0]))
            np.testing.assert_allclose(out, [6.0])
            assert seen == [1]
            # guard cache: second call with same signature goes straight to
            # eager (side effect runs again; no exception, no re-jit)
            g(jnp.asarray([4.0]))
            assert seen == [1, 1]

    def test_attribute_store_branch_not_captured(self):
        """lax.cond traces BOTH branches; a branch mutating object state
        must keep graph-break behavior, not convert (else the mutation
        runs unconditionally and leaks tracers)."""
        class Box:
            hits = 0

        box = Box()

        def f(x):
            if x.sum() > 0:
                y = x * 2.0
                box.hits = box.hits + 1   # side effect: blocks conversion
            else:
                y = -x
            return y

        g = to_static(f, full_graph=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = g(jnp.asarray([-1.0]))   # negative: branch NOT taken
        np.testing.assert_allclose(out, [1.0])
        assert box.hits == 0               # eager fallback, branch skipped

    def test_conversion_off_restores_old_behavior(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = -x
            return y

        g = to_static(f, convert_control_flow=False, full_graph=True)
        with pytest.raises(GraphBreakError):
            g(jnp.asarray([1.0]))


class TestLayerConversion:
    def test_model_with_bare_if_runs_unmodified(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if h.mean() > 0:       # bare data-dependent branch
                    out = h * 2.0
                else:
                    out = h * 0.5
                return out

        pt.seed(0)
        m = Gate()
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 4)).astype("float32"))
        eager = m(x)
        g = to_static(m)
        np.testing.assert_allclose(np.asarray(g(x)), np.asarray(eager),
                                   rtol=1e-6)

    def test_model_with_while_decode_loop(self):
        class Doubler(nn.Layer):
            def forward(self, x):
                while x.sum() < 30.0:
                    x = x * 2.0
                return x

        m = Doubler()
        x = jnp.asarray([1.0, 1.5])
        g = to_static(m)
        np.testing.assert_allclose(np.asarray(g(x)), np.asarray(m(x)))


class TestConvertFunction:
    def test_no_control_flow_unchanged(self):
        def f(x):
            return x * 2

        _, changed = convert_control_flow(f)
        assert not changed

    def test_closure_snapshot(self):
        scale = jnp.asarray(3.0)

        def make():
            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x
                return y
            return f

        f = make()
        g = to_static(f)
        np.testing.assert_allclose(g(jnp.asarray([2.0])), [6.0])


class TestForConversion:
    """round-4 (M95): for / break / continue + no-recompile guarantees
    (VERDICT r3 missing #3 / next #5)."""

    def test_for_range_traced_bound_single_trace(self):
        traces = [0]

        def f(x, n):
            traces[0] += 1
            s = jnp.zeros(())
            for i in range(n):
                s = s + x[i] * (i + 1)
            return s

        g = to_static(f)
        x = jnp.arange(8.0)
        for n in (3, 5, 8, 2):
            want = sum(float(x[i]) * (i + 1) for i in range(n))
            np.testing.assert_allclose(float(g(x, n)), want, rtol=1e-6)
        # the guard-cache property, jax-style: the bound is a traced
        # input of ONE while_loop program — new n values do NOT retrace
        assert traces[0] == 1, traces[0]

    def test_decode_loop_with_eos_break(self):
        traces = [0]

        def decode(toks, eos, n):
            traces[0] += 1
            count = jnp.zeros((), jnp.int32)
            for step in range(n):
                t = toks[step]
                if t == eos:
                    break
                count = count + 1
            return count

        d = to_static(decode)
        toks = jnp.asarray([3, 1, 4, 1, 5, 9, 2, 6])
        assert int(d(toks, 4, 8)) == 2
        assert int(d(toks, 9, 8)) == 5
        assert int(d(toks, 99, 8)) == 8   # EOS never fires
        assert int(d(toks, 99, 5)) == 5   # shorter budget, same trace
        assert traces[0] == 1, traces[0]

    def test_continue_lowered(self):
        def pos_sum(x):
            s = jnp.zeros(())
            for i in range(6):
                v = x[i]
                if v < 0:
                    continue
                s = s + v
            return s

        p = to_static(pos_sum)
        xv = jnp.asarray([1.0, -2.0, 3.0, -4.0, 5.0, -6.0])
        assert float(p(xv)) == 9.0

    def test_while_break_on_traced_pred(self):
        def wb(x):
            i = jnp.zeros((), jnp.int32)
            s = jnp.zeros(())
            while i < 10:
                if x[i] > 3:
                    break
                s = s + x[i]
                i = i + 1
            return s

        w = to_static(wb)
        arr = jnp.asarray([1., 2., 3., 4., 0., 0., 0., 0., 0., 0., 0.])
        assert float(w(arr)) == 6.0

    def test_for_over_traced_array_scans(self):
        def fa(xs):
            s = jnp.zeros(())
            for row in xs:
                s = s + row.max()
            return s

        a = to_static(fa)
        m = jnp.asarray([[1., 2.], [5., 3.], [0., 4.]])
        assert float(a(m)) == 11.0

    def test_concrete_loop_keeps_python_semantics(self):
        def conc(x, n):
            s = 0.0
            for i in range(n):
                if i == 2:
                    continue
                s = s + float(i)
            return s + float(x[0]) * 0

        c2, ok = convert_control_flow(conc)
        assert ok
        assert c2(np.ones(1), 5) == 8.0   # 0+1+3+4 — i==2 skipped

    def test_static_bool_arg_traces_at_most_twice(self):
        """Concrete-predicate guard behavior: with the branch value a
        static argument, jit's value-keyed cache IS the guard cache —
        many calls, at most one trace per distinct branch outcome."""
        traces = [0]

        def f(x, flag):
            traces[0] += 1
            if flag:
                y = x * 2
            else:
                y = x - 1
            return y

        g = to_static(f, static_argnums=(1,))
        for flag in (True, False, True, False, True, True, False):
            expect = 2.0 if flag else 0.0
            np.testing.assert_allclose(float(g(jnp.ones(()), flag)), expect)
        assert traces[0] == 2, traces[0]

    def test_break_in_nested_loop_stays_inner(self):
        def f(x):
            total = jnp.zeros(())
            for i in range(3):
                for j in range(4):
                    if x[i, j] < 0:
                        break
                    total = total + x[i, j]
            return total

        g = to_static(f)
        m = jnp.asarray([[1., 2., -1., 9.],   # stops after 1+2
                         [5., -1., 9., 9.],   # stops after 5
                         [1., 1., 1., 1.]])   # full row
        assert float(g(m)) == 12.0


class TestLoopLivenessAndSemantics:
    """round-4 review findings: liveness-carried values, once-evaluated
    range bounds, short-circuit test after break, traced zero step."""

    def test_body_store_read_after_loop_is_carried(self):
        def f(x):
            y = -1.0
            i = jnp.zeros((), jnp.int32)
            while i < 3:
                y = x * i
                i = i + 1
            return y

        g, ok = convert_control_flow(f)
        assert ok
        assert float(g(jnp.asarray(2.0))) == 4.0  # x*2, not the stale -1

    def test_for_target_read_after_loop_stays_python(self):
        def f(x, n):
            s = jnp.zeros(())
            for i in range(n):
                s = s + x[i]
            return s, i   # Python binds i after the loop

        g, ok = convert_control_flow(f)
        s, i = g(jnp.arange(4.0), 3)   # concrete n: Python semantics
        assert float(s) == 3.0 and i == 2

    def test_range_bounds_evaluated_once(self):
        def f(x):
            n = 4
            total = 0
            for i in range(n):
                n = 0          # must NOT affect the already-built range
                total = total + 1
                if x[0] < -99:
                    break      # forces the while-lowering path
            return total

        g, ok = convert_control_flow(f)
        assert ok
        assert g(np.ones(1)) == 4

    def test_break_does_not_rerun_side_effecting_test(self):
        def f(xs):
            calls = []
            i = 0
            v = None
            while (v := (xs[i] if i < len(xs) else None)) is not None:
                calls.append(v)
                if v == 2:
                    break
                i = i + 1
            return v, len(calls)

        g, ok = convert_control_flow(f)
        # a walrus-binding test DECLINES conversion (relocating it would
        # swallow the binding or re-run the side effect) — behavior must
        # be exactly Python's either way
        v, n = g([1, 2, 3])
        assert v == 2 and n == 2   # the test never re-ran after break

    def test_traced_zero_step_terminates(self):
        def f(x, st):
            s = jnp.zeros(())
            for i in range(5, 0, st):
                s = s + x[i]
            return s

        g = to_static(f)
        assert float(g(jnp.arange(6.0), -1)) == 5 + 4 + 3 + 2 + 1
        assert float(g(jnp.arange(6.0), 0)) == 0.0   # exits, no hang
