"""GPT/ERNIE family tests: training convergence, TP parity vs serial,
pipeline config compiles, generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.gpt import GPTConfig, gpt
from paddle_tpu.nn.layer import functional_call, raw_params


def _batch(b=4, s=16, vocab=256, seed=0):
    r = np.random.default_rng(seed)
    ids = r.integers(0, vocab, (b, s + 1)).astype("int32")
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:].astype("int64"))}


class TestGPT:
    def test_forward_shapes(self):
        pt.seed(0)
        m = gpt("tiny").eval()
        batch = _batch()
        logits = m(batch["input_ids"])
        assert logits.shape == (4, 16, 256)

    def test_train_memorizes(self):
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import AdamW

        pt.seed(0)
        m = gpt("tiny")
        opt = AdamW(learning_rate=5e-3, parameters=m.parameters())

        def loss_fn(model, batch):
            return model(batch["input_ids"], labels=batch["labels"])

        step = TrainStep(m, loss_fn, opt)
        state = step.init_state()
        batch = _batch(b=2, s=12)
        losses = []
        for _ in range(60):
            state, met = step(state, batch)
            losses.append(float(met["loss"]))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    def test_tp_matches_serial(self):
        """mp=4 sharded forward == serial forward (SURVEY §4 pattern)."""
        from paddle_tpu.distributed import fleet

        pt.seed(0)
        m = gpt("tiny").eval()
        batch = _batch(b=2, s=8)
        serial = np.asarray(m(batch["input_ids"]))

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"mp_degree": 4, "dp_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        try:
            params = raw_params(m)
            from paddle_tpu.jit import TrainStep
            from paddle_tpu.optimizer import AdamW
            step = TrainStep(m, lambda mm, b: mm(b["input_ids"]).sum(),
                             AdamW(parameters=m.parameters()))
            specs = step.param_specs()
            mesh = hcg.mesh
            from jax.sharding import NamedSharding
            with mesh:
                sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                           for k, v in params.items()}

                @jax.jit
                def fwd(p, ids):
                    return functional_call(m, p, ids, training=False)

                out = fwd(sharded, batch["input_ids"])
            np.testing.assert_allclose(np.asarray(out), serial, rtol=2e-3,
                                       atol=2e-4)
        finally:
            fleet._HYBRID_PARALLEL_GROUP = None

    def test_pipeline_config_compiles(self):
        from paddle_tpu.distributed import fleet

        pt.seed(1)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"pp_degree": 2, "dp_degree": 2,
                                   "mp_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        try:
            m = gpt(GPTConfig(vocab_size=64, hidden_size=32,
                              num_hidden_layers=4, num_attention_heads=2,
                              max_position_embeddings=32,
                              pipeline_stages=2, num_microbatches=2))
            batch = _batch(b=4, s=8, vocab=64, seed=2)
            from paddle_tpu.jit import TrainStep
            from paddle_tpu.optimizer import AdamW

            def loss_fn(model, b):
                return model(b["input_ids"], labels=b["labels"])

            step = TrainStep(m, loss_fn, AdamW(learning_rate=1e-3,
                                               parameters=m.parameters()))
            state = step.init_state()
            state, met = step(state, batch)
            assert np.isfinite(float(met["loss"]))
        finally:
            fleet._HYBRID_PARALLEL_GROUP = None

    def test_generate(self):
        pt.seed(3)
        m = gpt("tiny").eval()
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (1, 4))
                          .astype("int32"))
        out = m.generate(ids, max_new_tokens=5)
        assert out.shape == (1, 9)

    def test_presets_cover_baseline_13b(self):
        from paddle_tpu.models.gpt import PRESETS
        cfg = PRESETS["gpt3-13b"]
        assert cfg.hidden_size == 5120 and cfg.num_hidden_layers == 40


class TestGPTCachedGeneration:
    def test_cached_equals_recompute(self):
        pt.seed(0)
        m = gpt("tiny").eval()
        ids = jnp.asarray(np.random.default_rng(5).integers(
            0, 256, (2, 5)).astype("int32"))
        a = np.asarray(m.generate(ids, max_new_tokens=6, use_cache=False))
        b = np.asarray(m.generate(ids, max_new_tokens=6, use_cache=True))
        np.testing.assert_array_equal(a, b)

    def test_cache_respects_position_table(self):
        import pytest
        pt.seed(0)
        m = gpt("tiny")  # max_position_embeddings=128
        with pytest.raises(ValueError, match="max_position"):
            m.model.init_cache(1, 256)


class TestChunkedLoss:
    """loss_seq_chunks: rematerialized seq-chunked vocab CE must match the
    monolithic loss in value and gradient (llama.py _chunked_loss)."""

    def test_loss_and_grad_parity(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.models.llama import llama
        from paddle_tpu.nn.layer import functional_call, raw_params

        pt.seed(0)
        plain = llama("tiny")
        pt.seed(0)
        chunked = llama("tiny", loss_seq_chunks=4)
        ids = jax.random.randint(jax.random.key(1), (2, 64), 0,
                                 plain.cfg.vocab_size)
        labels = jnp.roll(ids, -1, 1)
        # mask some labels to exercise the valid-count denominator
        labels = labels.at[:, :5].set(-100)

        def lf(model):
            def f(p):
                return functional_call(model, p, ids, labels=labels)
            return f

        p = raw_params(plain)
        l1, g1 = jax.value_and_grad(lf(plain))(p)
        l2, g2 = jax.value_and_grad(lf(chunked))(raw_params(chunked))
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        flat1 = jax.tree_util.tree_leaves(g1)
        flat2 = jax.tree_util.tree_leaves(g2)
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-4)

    def test_indivisible_seq_falls_back(self):
        import paddle_tpu as pt
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        m = llama("tiny", loss_seq_chunks=7)  # 64 % 7 != 0 → monolithic path
        ids = jax.random.randint(jax.random.key(0), (1, 64), 0,
                                 m.cfg.vocab_size)
        loss = m(ids, labels=jnp.roll(ids, -1, 1))
        assert jnp.isfinite(loss)


class TestDecodeStrategies:
    """Reference generate() strategies: top-k/top-p filtering + repetition
    penalty (paddle generation_utils TopKProcess/TopPProcess)."""

    def _model(self):
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        return llama("tiny").eval()

    def test_filter_logits_top_k(self):
        from paddle_tpu.models.generation import filter_logits
        lg = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
        out = np.asarray(filter_logits(lg, top_k=2))
        assert np.isfinite(out[0, 1]) and np.isfinite(out[0, 2])
        assert out[0, 0] == -np.inf and out[0, 3] == -np.inf

    def test_filter_logits_top_p(self):
        from paddle_tpu.models.generation import filter_logits
        # softmax of [4, 2, 0] ≈ [.867, .117, .016]: top_p=.9 keeps 2
        lg = jnp.asarray([[4.0, 2.0, 0.0]])
        out = np.asarray(filter_logits(lg, top_p=0.9))
        assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
        assert out[0, 2] == -np.inf
        # top_p tiny still keeps the argmax
        out = np.asarray(filter_logits(lg, top_p=1e-6))
        assert np.isfinite(out[0, 0]) and out[0, 1] == -np.inf

    def test_filter_logits_repetition_penalty(self):
        from paddle_tpu.models.generation import filter_logits
        lg = jnp.asarray([[2.0, -2.0, 1.0]])
        seen = jnp.asarray([[1, 1, 0]])
        out = np.asarray(filter_logits(lg, repetition_penalty=2.0,
                                       seen=seen))
        np.testing.assert_allclose(out, [[1.0, -4.0, 1.0]])

    def test_generate_with_strategies_runs_both_paths(self):
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 256, size=(2, 8)))
        for kw in ({"top_k": 5, "temperature": 1.0},
                   {"top_p": 0.8, "temperature": 1.0},
                   {"repetition_penalty": 1.3},
                   {"decode_strategy": "greedy_search"}):
            a = m.generate(ids, max_new_tokens=4, use_cache=True, **kw)
            b = m.generate(ids, max_new_tokens=4, use_cache=False, **kw)
            assert a.shape == b.shape == (2, 12)
            if kw.get("temperature", 0.0) == 0.0:
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_repetition_penalty_discourages_repeats(self):
        """A greedy model stuck in a loop must break out with the
        penalty high."""
        m = self._model()
        ids = jnp.asarray([[5, 5, 5, 5, 5, 5, 5, 5]])
        plain = np.asarray(m.generate(ids, max_new_tokens=8))
        pen = np.asarray(m.generate(ids, max_new_tokens=8,
                                    repetition_penalty=8.0))
        # penalized run must differ from the unpenalized continuation
        assert not np.array_equal(plain, pen)

    def test_bad_strategy_rejected(self):
        m = self._model()
        with pytest.raises(ValueError, match="decode_strategy"):
            m.generate(jnp.zeros((1, 4), jnp.int32),
                       decode_strategy="contrastive_search")


def test_top_p_respects_temperature():
    """Reference order: temperature scaling BEFORE the nucleus cutoff —
    high temperature flattens the distribution and widens the kept set."""
    from paddle_tpu.models.generation import filter_logits
    lg = jnp.asarray([[3.0, 1.5, 0.0]])
    cold = np.asarray(filter_logits(lg, top_p=0.9, temperature=1.0))
    hot = np.asarray(filter_logits(lg, top_p=0.9, temperature=3.0))
    assert (np.isfinite(hot).sum() > np.isfinite(cold).sum())


class TestBeamSearch:
    def _model(self):
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        return llama("tiny").eval()

    def test_beam_equals_exhaustive_search(self):
        """num_beams >= vocab-path count: beam search must find the exact
        argmax sequence; verify against brute-force over all 2-token
        continuations scored by the model."""
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 256, size=(1, 6)))
        out = m.generate(ids, max_new_tokens=2,
                         decode_strategy="beam_search", num_beams=8)
        assert out.shape == (1, 8)

        # brute force: score every (t1 from top-8 first tokens, t2) pair
        logits1 = np.asarray(m(ids)[:, -1], np.float32)
        lp1 = np.log(np.exp(logits1[0] - logits1[0].max())
                     / np.exp(logits1[0] - logits1[0].max()).sum())
        top8 = np.argsort(lp1)[::-1][:8]
        best_score, best_pair = -np.inf, None
        for t1 in top8:
            seq = jnp.concatenate([ids, jnp.asarray([[t1]], ids.dtype)], 1)
            logits2 = np.asarray(m(seq)[:, -1], np.float32)[0]
            lp2 = np.log(np.exp(logits2 - logits2.max())
                         / np.exp(logits2 - logits2.max()).sum())
            t2 = int(np.argmax(lp2))
            s = lp1[t1] + lp2[t2]
            if s > best_score:
                best_score, best_pair = s, (int(t1), t2)
        assert tuple(np.asarray(out)[0, -2:]) == best_pair

    def test_beam_one_equals_greedy_argmax_path(self):
        """With enough beams the top beam's first token == greedy's."""
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, 256, size=(2, 5)))
        beam = np.asarray(m.generate(ids, max_new_tokens=1,
                                     decode_strategy="beam_search",
                                     num_beams=4))
        greedy = np.asarray(m.generate(ids, max_new_tokens=1))
        np.testing.assert_array_equal(beam, greedy)

    def test_beam_requires_cache(self):
        m = self._model()
        with pytest.raises(NotImplementedError, match="KV-cache"):
            m.generate(jnp.zeros((1, 4), jnp.int32), max_new_tokens=2,
                       decode_strategy="beam_search", num_beams=2,
                       use_cache=False)


class TestBeamSearchValidation:
    def _m(self):
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        return llama("tiny").eval()

    def test_num_beams_one_rejected(self):
        with pytest.raises(ValueError, match="num_beams > 1"):
            self._m().generate(jnp.zeros((1, 4), jnp.int32),
                               decode_strategy="beam_search")

    def test_beams_with_wrong_strategy_rejected(self):
        with pytest.raises(ValueError, match="requires"):
            self._m().generate(jnp.zeros((1, 4), jnp.int32),
                               decode_strategy="sampling", num_beams=4)

    def test_top_k_with_beam_rejected(self):
        with pytest.raises(NotImplementedError, match="top_k"):
            self._m().generate(jnp.zeros((1, 4), jnp.int32),
                               decode_strategy="beam_search", num_beams=2,
                               top_k=5)

    def test_max_len_validated(self):
        with pytest.raises(ValueError, match="max_len"):
            self._m().generate(jnp.zeros((1, 10), jnp.int32),
                               max_new_tokens=20, max_len=12,
                               decode_strategy="beam_search", num_beams=2)

    def test_repetition_penalty_applies_in_beam(self):
        m = self._m()
        ids = jnp.asarray([[7, 7, 7, 7, 7, 7]])
        plain = np.asarray(m.generate(ids, max_new_tokens=6,
                                      decode_strategy="beam_search",
                                      num_beams=3))
        pen = np.asarray(m.generate(ids, max_new_tokens=6,
                                    decode_strategy="beam_search",
                                    num_beams=3, repetition_penalty=8.0))
        assert not np.array_equal(plain, pen)


def test_num_beams_alone_triggers_beam_search():
    """num_beams>1 with default strategy runs beam search (reference
    behavior), never silent greedy."""
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    m = llama("tiny").eval()
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 256, (1, 5)))
    implicit = np.asarray(m.generate(ids, max_new_tokens=4, num_beams=3))
    explicit = np.asarray(m.generate(ids, max_new_tokens=4, num_beams=3,
                                     decode_strategy="beam_search"))
    np.testing.assert_array_equal(implicit, explicit)


class TestEosGeneration:
    """eos_token_id semantics (reference generate): a finished row pads to
    the fixed length; cached and uncached paths agree under greedy."""

    def _model(self):
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        return llama("tiny").eval()

    def _eos_of(self, m, ids):
        # pick the model's own first greedy token as "eos" so it triggers
        out = m.generate(ids, max_new_tokens=1)
        return int(np.asarray(out)[0, -1])

    def test_pad_after_eos_both_paths(self):
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 256, size=(1, 6)))
        eos = self._eos_of(m, ids)
        a = np.asarray(m.generate(ids, max_new_tokens=6, eos_token_id=eos,
                                  pad_token_id=0, use_cache=True))
        b = np.asarray(m.generate(ids, max_new_tokens=6, eos_token_id=eos,
                                  pad_token_id=0, use_cache=False))
        np.testing.assert_array_equal(a, b)
        # first new token IS eos here → everything after is pad
        assert a[0, 6] == eos and (a[0, 7:] == 0).all()

    def test_pad_defaults_to_eos(self):
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 256, size=(1, 6)))
        eos = self._eos_of(m, ids)
        out = np.asarray(m.generate(ids, max_new_tokens=5,
                                    eos_token_id=eos))
        assert (out[0, 6:] == eos).all()

    def test_beam_freezes_finished(self):
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 256, size=(1, 6)))
        # greedy continuation's token as eos: the top beam finishes at
        # step 1 and must pad from then on
        eos = self._eos_of(m, ids)
        out = np.asarray(m.generate(ids, max_new_tokens=5, num_beams=3,
                                    eos_token_id=eos, pad_token_id=0))
        row = out[0, 6:]
        # the frozen top beam's constant score keeps it winning: eos MUST
        # appear, and everything after it is pad
        assert eos in row, row
        i = list(row).index(eos)
        assert (row[i + 1:] == 0).all()

    def test_no_eos_unchanged(self):
        m = self._model()
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, 256, size=(2, 5)))
        a = np.asarray(m.generate(ids, max_new_tokens=4))
        b = np.asarray(m.generate(ids, max_new_tokens=4, eos_token_id=None))
        np.testing.assert_array_equal(a, b)


def test_gpt_partial_remat_num_layers():
    """recompute_num_layers parity with the llama family: only the first
    N layers wrapped; math unchanged."""
    from paddle_tpu.distributed.recompute import RecomputeWrapper
    from paddle_tpu.models.gpt import gpt

    def count(**kw):
        pt.seed(0)
        m = gpt("tiny", num_hidden_layers=4, **kw)
        return sum(isinstance(l, RecomputeWrapper) for l in m.model.h)

    assert count(use_recompute=True) == 4
    assert count(use_recompute=True, recompute_num_layers=2) == 2
    with pytest.raises(ValueError, match="recompute_num_layers"):
        count(use_recompute=True, recompute_num_layers=9)
    # ADVICE r5: set without use_recompute → warn, not silently ignore
    with pytest.warns(UserWarning, match="ignored because "
                                         "use_recompute=False"):
        assert count(use_recompute=False, recompute_num_layers=2) == 0
