"""Fused int4 dequant-in-matmul kernel (ops/pallas/int4_matmul).

Reference capability: the Cutlass fpA_intB int4 GEMM (SURVEY §2.1).
The kernel must be EXACT vs the XLA unpack formulation — both compute
x @ dequant(W) in f32 accumulation over identical nibble values.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn.quant import (weight_dequantize, weight_only_linear,
                                 weight_quantize)
from paddle_tpu.ops.pallas.int4_matmul import MAX_1D_K2, int4_matmul


@pytest.mark.parametrize("m,k,n", [(1, 256, 512), (8, 512, 384),
                                   (4, 128, 128), (3, 256, 256)])
def test_kernel_exact_vs_dequant_1d(m, k, n):
    rng = np.random.default_rng(0)
    w = rng.standard_normal((k, n)).astype(np.float32) * 0.05
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    q, s = weight_quantize(w, algo="weight_only_int4")
    ref = x @ weight_dequantize(q, s, algo="weight_only_int4")
    got = int4_matmul(x, q, s, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_exact_2d_grid_path():
    # contraction tall enough to take the 2-D accumulator path
    k = 2 * MAX_1D_K2 + 512
    rng = np.random.default_rng(1)
    w = rng.standard_normal((k, 256)).astype(np.float32) * 0.02
    x = jnp.asarray(rng.standard_normal((2, k)), jnp.float32)
    q, s = weight_quantize(w, algo="weight_only_int4")
    ref = x @ weight_dequantize(q, s, algo="weight_only_int4")
    got = int4_matmul(x, q, s, block_k2=512, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_shape_validation():
    q = jnp.zeros((8, 128), jnp.int8)
    with pytest.raises(ValueError, match="K"):
        int4_matmul(jnp.zeros((1, 100)), q, jnp.ones((128,)), interpret=True)
    with pytest.raises(ValueError, match="scale"):
        int4_matmul(jnp.zeros((1, 16)), q, jnp.ones((4,)), interpret=True)


def test_weight_only_linear_kernel_dispatch(monkeypatch):
    """The kernel-dispatch branch of weight_only_linear (lead-dim
    reshape, bias add, per-channel gating) — forced on with the kernel in
    interpret mode so it runs on the CPU suite."""
    import functools

    from paddle_tpu.nn import quant as QN
    from paddle_tpu.ops.pallas import int4_matmul as kernel_mod

    monkeypatch.setattr(QN, "_use_int4_kernel", lambda: True)
    monkeypatch.setattr(
        kernel_mod, "int4_matmul",
        functools.partial(int4_matmul, block_n=128, interpret=True))

    rng = np.random.default_rng(2)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    bias = rng.standard_normal((128,)).astype(np.float32)
    x3d = jnp.asarray(rng.standard_normal((2, 3, 64)), jnp.float32)
    q, s = weight_quantize(w, algo="weight_only_int4")
    got = weight_only_linear(x3d, q, bias=bias, weight_scale=s,
                             weight_dtype="int4")
    ref = x3d @ weight_dequantize(q, s, algo="weight_only_int4") + bias
    assert got.shape == (2, 3, 128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # groupwise scales must NOT take the kernel (scale.ndim == 2)
    qg, sg = weight_quantize(w, algo="weight_only_int4", group_size=32)
    got_g = weight_only_linear(x3d, qg, weight_scale=sg, weight_dtype="int4",
                               group_size=32)
    assert got_g.shape == (2, 3, 128)

    # prefill-sized token counts must NOT take the kernel (n_tokens > 256):
    # swap in a tripwire so mis-routing FAILS rather than coincidentally
    # matching numerics
    def _boom(*a, **k):
        raise AssertionError("prefill-sized call routed to the int4 kernel")
    monkeypatch.setattr(kernel_mod, "int4_matmul", _boom)
    xbig = jnp.asarray(rng.standard_normal((300, 64)), jnp.float32)
    got_big = weight_only_linear(xbig, q, weight_scale=s,
                                 weight_dtype="int4")
    ref_big = xbig @ weight_dequantize(q, s, algo="weight_only_int4")
    np.testing.assert_allclose(np.asarray(got_big), np.asarray(ref_big),
                               rtol=2e-5, atol=2e-5)
    # ...and the groupwise guard with the tripwire still armed
    got_g2 = weight_only_linear(x3d, qg, weight_scale=sg,
                                weight_dtype="int4", group_size=32)
    np.testing.assert_allclose(np.asarray(got_g2), np.asarray(got_g))


def test_column_parallel_kernel_matches_xla_on_mesh(monkeypatch):
    """Multi-chip serving path: QuantizedColumnParallelLinear's
    shard_map'd int4 kernel (mp-split columns, no reduction) must equal
    the XLA path under the same mesh."""
    import functools

    import paddle_tpu as pt
    import paddle_tpu.nn.quant as QN
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mp_layers import ColumnParallelLinear
    from paddle_tpu.ops.pallas import int4_matmul as kernel_mod

    fleet._reset()
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"mp_degree": 2, "dp_degree": 4}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        pt.seed(0)
        host = ColumnParallelLinear(64, 256, has_bias=True)
        q = QN.QuantizedColumnParallelLinear(host, algo="weight_only_int4")
        x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 1, 64)),
                        jnp.float32)
        with hcg.mesh:
            ref = np.asarray(q(x))                      # XLA path
        monkeypatch.setattr(QN, "_use_int4_kernel", lambda: True)
        monkeypatch.setattr(
            kernel_mod, "int4_matmul",
            functools.partial(int4_matmul, block_n=128, interpret=True))
        with hcg.mesh:
            got = np.asarray(q(x))                      # shard_map kernel
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    finally:
        fleet._reset()
