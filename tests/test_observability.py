"""Runtime telemetry subsystem (paddle_tpu/observability): registry,
sinks, StepMonitor math, recompile sentinel, collective accounting,
preemption events.

Reference capability: PaddlePaddle's profiler/monitor stack (SURVEY
§5.5) — always-on runtime statistics.  Everything here runs on the CPU
backend; MFU uses the nominal 1e12 cpu peak from observability/mfu.py.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import warnings

import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.observability as obs
from paddle_tpu.observability import _state as obs_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry():
    sink = obs.InMemorySink()
    tel = obs.enable(sinks=[sink], storm_threshold=2, storm_window_s=60.0)
    yield tel, sink
    obs.disable()


@pytest.fixture(autouse=True)
def _always_disabled_after():
    yield
    obs.disable()


# -- registry ----------------------------------------------------------------

def test_registry_counter_gauge():
    reg = obs.MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(41)
    reg.gauge("g").set(3.5)
    assert reg.counter("c").value == 42
    assert reg.gauge("g").value == 3.5
    assert reg.snapshot()["c"] == 42


def test_registry_kind_collision_raises():
    reg = obs.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_histogram_rolling_percentiles():
    reg = obs.MetricsRegistry()
    h = reg.histogram("h", window=1000)
    for v in range(1, 101):   # 1..100
        h.observe(v)
    # nearest-rank: p50 = 50th smallest, p95 = 95th smallest
    assert h.percentile(50) == 50
    assert h.percentile(95) == 95
    snap = reg.snapshot()["h"]
    assert snap["count"] == 100 and snap["p50"] == 50 and snap["p95"] == 95
    # rolling: a small window only sees the latest observations
    h2 = obs.Histogram("h2", window=10)
    for v in range(1, 101):
        h2.observe(v)
    assert h2.percentile(50) == 95  # window holds 91..100


def test_registry_thread_safety():
    reg = obs.MetricsRegistry()

    def work():
        for _ in range(2000):
            reg.counter("n").inc()
            reg.histogram("hh").observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("n").value == 16000
    assert reg.histogram("hh").count == 16000


# -- sinks -------------------------------------------------------------------

def test_jsonl_sink_roundtrip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    arr = jnp.float32(2.5)   # before enable: its jit is not an event
    tel = obs.enable(jsonl_path=path)
    tel.emit({"event": "custom", "n": 1, "arr": arr})
    obs.disable()   # metrics snapshot + close
    lines = [json.loads(l) for l in open(path)]
    custom = next(l for l in lines if l["event"] == "custom")
    assert custom["n"] == 1 and custom["arr"] == 2.5 and "ts" in custom
    assert lines[-1]["event"] == "metrics"


def test_disabled_by_default_and_hooks_clear():
    assert not obs.enabled()
    assert obs_state.MONITOR[0] is None
    assert obs_state.COLLECTIVE[0] is None
    assert obs_state.EMIT[0] is None
    obs.emit_event("nothing")  # no-op, must not raise
    tel = obs.enable()
    assert obs.enabled() and obs_state.MONITOR[0] is tel.monitor
    obs.disable()
    assert not obs.enabled() and obs_state.MONITOR[0] is None


# -- StepMonitor -------------------------------------------------------------

def _tiny_trainstep():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.jit import TrainStep
    model = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()
    step = TrainStep(model, loss, opt)
    state = step.init_state()
    batch = {"x": jnp.ones((4, 8)), "y": jnp.zeros((4, 8))}
    return step, state, batch


def test_step_monitor_emits_step_events(telemetry):
    tel, sink = telemetry
    step, state, batch = _tiny_trainstep()
    for _ in range(5):
        state, _ = step(state, batch)
    events = sink.events("step")
    assert len(events) == 5
    for ev in events:
        assert ev["site"] == "TrainStep(Linear)"
        assert ev["wall_ms"] > 0 and ev["interval_ms"] > 0
        assert ev["tokens"] == 32                    # 4 x 8 batch
        assert "tokens_per_sec" in ev and "mfu" in ev
    assert events[0]["warmup"] is True               # compile step
    assert events[-1]["warmup"] is False
    # registry mirrors: count + rolling interval histogram
    reg = tel.registry
    assert reg.counter("step[TrainStep(Linear)].count").value == 5
    assert reg.histogram("step[TrainStep(Linear)].interval_ms").count == 4


def test_step_monitor_mfu_matches_bench_math(telemetry):
    """Runtime MFU and bench.py's MFU use the same formula by
    construction: recompute the event's mfu from its own tokens_per_sec
    and the shared flops-per-token function."""
    tel, sink = telemetry
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import causal_lm_loss, llama
    from paddle_tpu.observability.mfu import (causal_lm_flops_per_token,
                                              peak_flops)
    pt.seed(0)
    model = llama("tiny", max_position_embeddings=16)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    step = TrainStep(model, causal_lm_loss, opt)
    state = step.init_state(seed=0)
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0,
                             model.cfg.vocab_size)
    batch = {"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)}
    for _ in range(5):
        state, _ = step(state, batch)
    events = sink.events("step")
    assert len(events) >= 5    # the 5-step llama smoke contract
    assert all("tokens_per_sec" in e and "mfu" in e for e in events)
    ev = events[-1]
    assert ev["tokens"] == 32                        # 2 x 16
    fpt = causal_lm_flops_per_token(model.cfg.num_params(),
                                    model.cfg.num_hidden_layers,
                                    model.cfg.hidden_size, 16)
    expect = ev["tokens_per_sec"] * fpt / peak_flops()
    assert ev["mfu"] == pytest.approx(expect, rel=1e-3, abs=1e-4)


def test_hapi_model_feeds_monitor(telemetry):
    tel, sink = telemetry
    from paddle_tpu import nn, optimizer
    net = nn.Linear(4, 2)
    model = pt.Model(net)
    model.prepare(optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters()),
                  loss=lambda pred, label: ((pred - label) ** 2).mean())
    x = jnp.ones((4, 4))
    y = jnp.zeros((4, 2))
    for _ in range(3):
        model.train_batch([x], [y])
    events = [e for e in sink.events("step")
              if e["site"] == "hapi.Model(Linear)"]
    assert len(events) == 3
    assert events[-1]["tokens"] == 16                # 4 x 4 input


def test_engine_fit_emits_steps_and_epochs(telemetry):
    tel, sink = telemetry
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer
    model = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()
    engine = dist.Engine(model, loss=loss, optimizer=opt)
    data = [{"x": jnp.ones((2, 8)), "y": jnp.zeros((2, 8))}] * 3
    engine.fit(data, epochs=2)
    steps = sink.events("step")
    epochs = sink.events("epoch")
    assert len(steps) == 6 and len(epochs) == 2
    assert epochs[0]["steps"] == 3 and "loss" in epochs[0]


# -- recompile sentinel ------------------------------------------------------

def test_recompile_sentinel_counts_shape_change(telemetry):
    tel, sink = telemetry
    before = tel.sentinel.compiles()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones((3,)))
    f(jnp.ones((3,)))        # cache hit: no compile
    f(jnp.ones((5,)))        # shape change: recompile
    assert tel.sentinel.compiles() - before >= 2
    compiles = sink.events("compile")
    assert len(compiles) >= 2
    assert all(c["duration_ms"] >= 0 for c in compiles)
    assert tel.registry.counter("compile.count").value >= 2


def test_recompile_storm_warning(telemetry):
    """The classic shape-churn failure: one jit site compiling on every
    call trips the loud warning (threshold 2 in the fixture)."""
    tel, sink = telemetry
    f = jax.jit(lambda x: x + 1)
    # inputs built OUTSIDE the scope: jnp.ones itself compiles per shape
    # and those compiles must not be attributed to the churny site
    xs = [jnp.ones((n,)) for n in (3, 5, 7, 9, 11)]
    with pytest.warns(obs.RecompileStormWarning, match="recompile storm"):
        with tel.sentinel.site("churny-step"):
            for x in xs:
                f(x)
    storms = sink.events("recompile_storm")
    assert storms and storms[0]["site"] == "churny-step"
    assert storms[0]["compiles_after_warmup"] >= 2
    assert tel.sentinel.compiles("churny-step") == 5


def test_trainstep_shape_churn_attributed(telemetry):
    """Shape churn THROUGH TrainStep is attributed to its site and
    trips the storm warning without any manual site scope."""
    tel, sink = telemetry
    step, state, _ = _tiny_trainstep()
    with pytest.warns(obs.RecompileStormWarning):
        for b in (2, 3, 4, 5):   # batch-size churn: recompile per step
            batch = {"x": jnp.ones((b, 8)), "y": jnp.zeros((b, 8))}
            state, _ = step(state, batch)
    sites = {c["site"] for c in sink.events("compile")}
    assert "TrainStep(Linear)" in sites
    storms = sink.events("recompile_storm")
    assert any(s["site"] == "TrainStep(Linear)" for s in storms)


def test_unattributed_compiles_do_not_storm(telemetry):
    tel, sink = telemetry
    f = jax.jit(lambda x: x - 1)
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.RecompileStormWarning)
        for n in (2, 3, 4, 5, 6):   # no site scope: counted, never warns
            f(jnp.ones((n,)))
    assert tel.sentinel.compiles() >= 5
    assert not sink.events("recompile_storm")


# -- collective accounting ---------------------------------------------------

def test_collective_byte_counters(telemetry):
    tel, sink = telemetry
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": jax.device_count()}
    fleet.init(strategy=strategy)
    try:
        x = jnp.ones((4, 4), jnp.float32)
        dist.all_reduce(x)
        dist.all_reduce(x)
        reg = tel.registry
        assert reg.counter("collective.all_reduce.calls").value == 2
        assert reg.counter("collective.all_reduce.bytes").value == 2 * 64
        # paddle-style list signature: the payload is the SECOND arg (the
        # first is the empty output list) — bytes must still be counted
        out = []
        dist.all_gather(out, x)
        assert reg.counter("collective.all_gather.bytes").value == 64
    finally:
        fleet._reset()
    obs.disable()
    # snapshot carried into the final metrics event
    snap = [e for e in sink.events("metrics")][-1]["metrics"]
    assert snap["collective.all_reduce.bytes"] == 128


# -- preemption events -------------------------------------------------------

def test_preemption_event(telemetry):
    tel, sink = telemetry
    from paddle_tpu.launch.preempt import PreemptionGuard
    saved = []
    guard = PreemptionGuard(save_fn=lambda: saved.append(1))
    with guard:
        signal.raise_signal(signal.SIGTERM)
        signal.raise_signal(signal.SIGTERM)   # repeat signal: one event
    assert guard.preempted and saved == [1]
    events = sink.events("preemption")
    assert len(events) == 1
    assert events[0]["reason"] == "SIGTERM"
    assert "ts" in events[0] and "step" in events[0]


# -- telemetry_report tool ---------------------------------------------------

def test_telemetry_report_folds_jsonl(tmp_path, telemetry):
    tel, sink = telemetry
    path = str(tmp_path / "run.jsonl")
    js = obs.JsonlSink(path)
    tel.sinks.append(js)
    step, state, batch = _tiny_trainstep()
    for _ in range(4):
        state, _ = step(state, batch)
    tel.flush()
    js.close()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         path], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| TrainStep(Linear) |" in r.stdout
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["sites"]["TrainStep(Linear)"]["steps"] == 4
    assert summary["compiles"]  # the TrainStep compile was attributed
