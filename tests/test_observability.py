"""Runtime telemetry subsystem (paddle_tpu/observability): registry,
sinks, StepMonitor math, recompile sentinel, collective accounting,
preemption events.

Reference capability: PaddlePaddle's profiler/monitor stack (SURVEY
§5.5) — always-on runtime statistics.  Everything here runs on the CPU
backend; MFU uses the nominal 1e12 cpu peak from observability/mfu.py.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import warnings

import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.observability as obs
from paddle_tpu.observability import _state as obs_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry(tmp_path):
    sink = obs.InMemorySink()
    # postmortem path pinned into tmp (the preemption test drains the
    # ring); crash hooks off — pytest owns excepthook/atexit
    tel = obs.enable(sinks=[sink], storm_threshold=2, storm_window_s=60.0,
                     postmortem_path=str(tmp_path / "t.postmortem"),
                     crash_hooks=False)
    yield tel, sink
    obs.disable()


@pytest.fixture(autouse=True)
def _always_disabled_after():
    yield
    obs.disable()


# -- registry ----------------------------------------------------------------

def test_registry_counter_gauge():
    reg = obs.MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(41)
    reg.gauge("g").set(3.5)
    assert reg.counter("c").value == 42
    assert reg.gauge("g").value == 3.5
    assert reg.snapshot()["c"] == 42


def test_registry_kind_collision_raises():
    reg = obs.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_histogram_rolling_percentiles():
    reg = obs.MetricsRegistry()
    h = reg.histogram("h", window=1000)
    for v in range(1, 101):   # 1..100
        h.observe(v)
    # nearest-rank: p50 = 50th smallest, p95 = 95th smallest
    assert h.percentile(50) == 50
    assert h.percentile(95) == 95
    snap = reg.snapshot()["h"]
    assert snap["count"] == 100 and snap["p50"] == 50 and snap["p95"] == 95
    # rolling: a small window only sees the latest observations
    h2 = obs.Histogram("h2", window=10)
    for v in range(1, 101):
        h2.observe(v)
    assert h2.percentile(50) == 95  # window holds 91..100


def test_registry_thread_safety():
    reg = obs.MetricsRegistry()

    def work():
        for _ in range(2000):
            reg.counter("n").inc()
            reg.histogram("hh").observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("n").value == 16000
    assert reg.histogram("hh").count == 16000


# -- sinks -------------------------------------------------------------------

def test_jsonl_sink_roundtrip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    arr = jnp.float32(2.5)   # before enable: its jit is not an event
    tel = obs.enable(jsonl_path=path)
    tel.emit({"event": "custom", "n": 1, "arr": arr})
    obs.disable()   # metrics snapshot + close
    lines = [json.loads(l) for l in open(path)]
    custom = next(l for l in lines if l["event"] == "custom")
    assert custom["n"] == 1 and custom["arr"] == 2.5 and "ts" in custom
    assert lines[-1]["event"] == "metrics"


def test_disabled_by_default_and_hooks_clear():
    assert not obs.enabled()
    assert obs_state.MONITOR[0] is None
    assert obs_state.COLLECTIVE[0] is None
    assert obs_state.EMIT[0] is None
    assert obs_state.SPAN[0] is None
    assert obs_state.RECORDER[0] is None
    assert obs_state.POSTMORTEM[0] is None
    obs.emit_event("nothing")  # no-op, must not raise
    tel = obs.enable(crash_hooks=False)
    assert obs.enabled() and obs_state.MONITOR[0] is tel.monitor
    assert obs_state.RECORDER[0] is tel.recorder
    assert obs_state.SPAN[0] is not None
    obs.disable()
    assert not obs.enabled() and obs_state.MONITOR[0] is None
    assert obs_state.SPAN[0] is None and obs_state.RECORDER[0] is None
    assert obs_state.POSTMORTEM[0] is None


# -- StepMonitor -------------------------------------------------------------

def _tiny_trainstep():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.jit import TrainStep
    model = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()
    step = TrainStep(model, loss, opt)
    state = step.init_state()
    batch = {"x": jnp.ones((4, 8)), "y": jnp.zeros((4, 8))}
    return step, state, batch


def test_step_monitor_emits_step_events(telemetry):
    tel, sink = telemetry
    step, state, batch = _tiny_trainstep()
    for _ in range(5):
        state, _ = step(state, batch)
    events = sink.events("step")
    assert len(events) == 5
    for ev in events:
        assert ev["site"] == "TrainStep(Linear)"
        assert ev["wall_ms"] > 0 and ev["interval_ms"] > 0
        assert ev["tokens"] == 32                    # 4 x 8 batch
        assert "tokens_per_sec" in ev and "mfu" in ev
    assert events[0]["warmup"] is True               # compile step
    assert events[-1]["warmup"] is False
    # registry mirrors: count + rolling interval histogram
    reg = tel.registry
    assert reg.counter("step[TrainStep(Linear)].count").value == 5
    assert reg.histogram("step[TrainStep(Linear)].interval_ms").count == 4


def test_step_monitor_mfu_matches_bench_math(telemetry):
    """Runtime MFU and bench.py's MFU use the same formula by
    construction: recompute the event's mfu from its own tokens_per_sec
    and the shared flops-per-token function."""
    tel, sink = telemetry
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import causal_lm_loss, llama
    from paddle_tpu.observability.mfu import (causal_lm_flops_per_token,
                                              peak_flops)
    pt.seed(0)
    model = llama("tiny", max_position_embeddings=16)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    step = TrainStep(model, causal_lm_loss, opt)
    state = step.init_state(seed=0)
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0,
                             model.cfg.vocab_size)
    batch = {"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)}
    for _ in range(5):
        state, _ = step(state, batch)
    events = sink.events("step")
    assert len(events) >= 5    # the 5-step llama smoke contract
    assert all("tokens_per_sec" in e and "mfu" in e for e in events)
    ev = events[-1]
    assert ev["tokens"] == 32                        # 2 x 16
    fpt = causal_lm_flops_per_token(model.cfg.num_params(),
                                    model.cfg.num_hidden_layers,
                                    model.cfg.hidden_size, 16)
    expect = ev["tokens_per_sec"] * fpt / peak_flops()
    assert ev["mfu"] == pytest.approx(expect, rel=1e-3, abs=1e-4)


def test_hapi_model_feeds_monitor(telemetry):
    tel, sink = telemetry
    from paddle_tpu import nn, optimizer
    net = nn.Linear(4, 2)
    model = pt.Model(net)
    model.prepare(optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters()),
                  loss=lambda pred, label: ((pred - label) ** 2).mean())
    x = jnp.ones((4, 4))
    y = jnp.zeros((4, 2))
    for _ in range(3):
        model.train_batch([x], [y])
    events = [e for e in sink.events("step")
              if e["site"] == "hapi.Model(Linear)"]
    assert len(events) == 3
    assert events[-1]["tokens"] == 16                # 4 x 4 input


def test_engine_fit_emits_steps_and_epochs(telemetry):
    tel, sink = telemetry
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer
    model = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()
    engine = dist.Engine(model, loss=loss, optimizer=opt)
    data = [{"x": jnp.ones((2, 8)), "y": jnp.zeros((2, 8))}] * 3
    engine.fit(data, epochs=2)
    steps = sink.events("step")
    epochs = sink.events("epoch")
    assert len(steps) == 6 and len(epochs) == 2
    assert epochs[0]["steps"] == 3 and "loss" in epochs[0]


# -- recompile sentinel ------------------------------------------------------

def test_recompile_sentinel_counts_shape_change(telemetry):
    tel, sink = telemetry
    before = tel.sentinel.compiles()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones((3,)))
    f(jnp.ones((3,)))        # cache hit: no compile
    f(jnp.ones((5,)))        # shape change: recompile
    assert tel.sentinel.compiles() - before >= 2
    compiles = sink.events("compile")
    assert len(compiles) >= 2
    assert all(c["duration_ms"] >= 0 for c in compiles)
    assert tel.registry.counter("compile.count").value >= 2


def test_recompile_storm_warning(telemetry):
    """The classic shape-churn failure: one jit site compiling on every
    call trips the loud warning (threshold 2 in the fixture)."""
    tel, sink = telemetry
    f = jax.jit(lambda x: x + 1)
    # inputs built OUTSIDE the scope: jnp.ones itself compiles per shape
    # and those compiles must not be attributed to the churny site
    xs = [jnp.ones((n,)) for n in (3, 5, 7, 9, 11)]
    with pytest.warns(obs.RecompileStormWarning, match="recompile storm"):
        with tel.sentinel.site("churny-step"):
            for x in xs:
                f(x)
    storms = sink.events("recompile_storm")
    assert storms and storms[0]["site"] == "churny-step"
    assert storms[0]["compiles_after_warmup"] >= 2
    assert tel.sentinel.compiles("churny-step") == 5


def test_trainstep_shape_churn_attributed(telemetry):
    """Shape churn THROUGH TrainStep is attributed to its site and
    trips the storm warning without any manual site scope."""
    tel, sink = telemetry
    step, state, _ = _tiny_trainstep()
    with pytest.warns(obs.RecompileStormWarning):
        for b in (2, 3, 4, 5):   # batch-size churn: recompile per step
            batch = {"x": jnp.ones((b, 8)), "y": jnp.zeros((b, 8))}
            state, _ = step(state, batch)
    sites = {c["site"] for c in sink.events("compile")}
    assert "TrainStep(Linear)" in sites
    storms = sink.events("recompile_storm")
    assert any(s["site"] == "TrainStep(Linear)" for s in storms)


def test_unattributed_compiles_do_not_storm(telemetry):
    tel, sink = telemetry
    f = jax.jit(lambda x: x - 1)
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.RecompileStormWarning)
        for n in (2, 3, 4, 5, 6):   # no site scope: counted, never warns
            f(jnp.ones((n,)))
    assert tel.sentinel.compiles() >= 5
    assert not sink.events("recompile_storm")


# -- collective accounting ---------------------------------------------------

def test_collective_byte_counters(telemetry):
    tel, sink = telemetry
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": jax.device_count()}
    fleet.init(strategy=strategy)
    try:
        x = jnp.ones((4, 4), jnp.float32)
        dist.all_reduce(x)
        dist.all_reduce(x)
        reg = tel.registry
        assert reg.counter("collective.all_reduce.calls").value == 2
        assert reg.counter("collective.all_reduce.bytes").value == 2 * 64
        # paddle-style list signature: the payload is the SECOND arg (the
        # first is the empty output list) — bytes must still be counted
        out = []
        dist.all_gather(out, x)
        assert reg.counter("collective.all_gather.bytes").value == 64
    finally:
        fleet._reset()
    obs.disable()
    # snapshot carried into the final metrics event
    snap = [e for e in sink.events("metrics")][-1]["metrics"]
    assert snap["collective.all_reduce.bytes"] == 128


# -- preemption events -------------------------------------------------------

def test_preemption_event(telemetry):
    tel, sink = telemetry
    from paddle_tpu.launch.preempt import PreemptionGuard
    saved = []
    guard = PreemptionGuard(save_fn=lambda: saved.append(1))
    with guard:
        signal.raise_signal(signal.SIGTERM)
        signal.raise_signal(signal.SIGTERM)   # repeat signal: one event
    assert guard.preempted and saved == [1]
    events = sink.events("preemption")
    assert len(events) == 1
    assert events[0]["reason"] == "SIGTERM"
    assert "ts" in events[0] and "step" in events[0]


def test_preemption_drains_postmortem(telemetry, tmp_path):
    """The first SIGTERM drains the flight ring to the .postmortem file
    from inside the signal handler — a preempted run is never blind even
    if the SIGKILL follow-up lands before the grace window ends."""
    tel, sink = telemetry
    from paddle_tpu.launch.preempt import PreemptionGuard
    tel.emit({"event": "custom", "marker": 17})
    with PreemptionGuard():
        signal.raise_signal(signal.SIGTERM)
    pm_path = tmp_path / "t.postmortem"   # fixture-pinned path
    assert pm_path.exists()
    lines = [json.loads(l) for l in open(pm_path)]
    assert lines[0]["event"] == "postmortem"
    assert lines[0]["reason"] == "preemption:SIGTERM"
    kinds = [l["event"] for l in lines]
    assert "thread_stack" in kinds and "metrics" in kinds
    assert any(l.get("marker") == 17 for l in lines)   # ring drained
    # the preemption event itself was emitted first, so it is in the ring
    assert any(l.get("event") == "preemption" for l in lines)


# -- flight recorder ---------------------------------------------------------

def test_flight_recorder_ring_bounded():
    rec = obs.FlightRecorder(capacity=8)
    for i in range(50):
        rec.record("beat", i=i)
    assert len(rec) == 8 and rec.total == 50
    events = rec.snapshot()
    assert [e["i"] for e in events] == list(range(42, 50))
    assert rec.age_s() < 5.0


def test_flight_recorder_sees_events_and_breadcrumbs(telemetry):
    """Every emitted event lands in the ring, and the step span leaves
    begin breadcrumbs even though the step event carries the numbers."""
    tel, sink = telemetry
    step, state, batch = _tiny_trainstep()
    for _ in range(2):
        state, _ = step(state, batch)
    rec = obs.get_flight_recorder()
    assert rec is tel.recorder and rec is not None
    kinds = [e["event"] for e in rec.snapshot()]
    assert "step" in kinds           # emitted event recorded
    assert "span_begin" in kinds     # breadcrumb BEFORE the step ran
    begins = [e for e in rec.snapshot() if e["event"] == "span_begin"]
    assert any(e["name"] == "TrainStep(Linear)" for e in begins)


# -- trace spans -------------------------------------------------------------

def test_span_disabled_is_noop():
    assert obs_state.SPAN[0] is None
    with obs.span("nothing"):
        pass                          # no telemetry, no profiler: no-op


def test_span_event_registry_breadcrumb(telemetry):
    tel, sink = telemetry
    with obs.span("my.op", tag="x"):
        pass
    ev = sink.events("span")
    assert len(ev) == 1
    assert ev[0]["name"] == "my.op" and ev[0]["tag"] == "x"
    assert ev[0]["ms"] >= 0
    assert tel.registry.histogram("span[my.op].ms").count == 1
    kinds = [e["event"] for e in tel.recorder.snapshot()]
    assert "span_begin" in kinds
    # emitted span event is in the ring once (no duplicate span_end)
    assert kinds.count("span") == 1 and "span_end" not in kinds


def test_span_feeds_profiler_chrome_trace(tmp_path):
    """The profiler bridge works WITHOUT telemetry: a span inside a
    recording Profiler lands on the host timeline under the same name —
    one vocabulary for JSONL and the deep-dive trace."""
    from paddle_tpu import profiler
    assert not obs.enabled()
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    assert profiler.is_recording()
    with obs.span("bridge.op"):
        pass
    rows = {r[0] for r in prof.aggregate()}
    assert "bridge.op" in rows
    path = str(tmp_path / "trace.json")
    prof.export(path)
    prof.stop()
    names = {e["name"] for e in profiler.load_profiler_result(path)["traceEvents"]}
    assert "bridge.op" in names


def test_ckpt_and_collective_spans(telemetry, tmp_path):
    tel, sink = telemetry
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    path = str(tmp_path / "obj.pd")
    pt.save({"w": jnp.ones((3,))}, path)
    pt.load(path)
    names = [e["name"] for e in sink.events("span")]
    assert "ckpt.save" in names and "ckpt.load" in names
    # eager collective span: begin breadcrumb lands before the op blocks
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": jax.device_count()}
    fleet.init(strategy=strategy)
    try:
        dist.all_reduce(jnp.ones((2, 2)))
    finally:
        fleet._reset()
    names = [e["name"] for e in sink.events("span")]
    assert "collective.all_reduce" in names
    begins = [e["name"] for e in tel.recorder.snapshot()
              if e["event"] == "span_begin"]
    assert "collective.all_reduce" in begins


def test_engine_epoch_span(telemetry):
    tel, sink = telemetry
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer
    model = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()
    engine = dist.Engine(model, loss=loss, optimizer=opt)
    data = [{"x": jnp.ones((2, 8)), "y": jnp.zeros((2, 8))}] * 2
    engine.fit(data, epochs=2)
    spans = [e for e in sink.events("span")
             if e["name"] == "Engine.fit.epoch"]
    assert len(spans) == 2 and spans[1]["epoch"] == 1


# -- hang watchdog -----------------------------------------------------------

def test_watchdog_fires_on_wedged_step(telemetry, tmp_path):
    """Acceptance: a wedged fake step trips the watchdog within its
    deadline and the post-mortem holds thread stacks, the last-N flight
    events, and a registry snapshot."""
    tel, sink = telemetry
    import time
    pm = str(tmp_path / "hang.postmortem")
    wd = obs.HangWatchdog(deadline_s=0.3, recorder=tel.recorder,
                          registry=tel.registry, emit=tel.emit,
                          postmortem_path=pm)
    wd.start()
    try:
        tel.registry.counter("sentinel.metric").inc(5)

        def wedged():
            time.sleep(1.0)       # > deadline: the step enters, then hangs
            return None, {}

        tel.monitor.timed_step("TrainStep(Wedged)", None,
                               {"x": jnp.ones((2, 4))}, wedged)
    finally:
        wd.stop()
    assert wd.fired == 1          # one dump per stall episode
    assert wd.last_dump == pm and os.path.exists(pm)
    lines = [json.loads(l) for l in open(pm)]
    head = lines[0]
    assert head["event"] == "postmortem" and "hang" in head["reason"]
    stacks = [l for l in lines if l["event"] == "thread_stack"]
    assert stacks
    # the wedged thread's stack shows WHERE it is stuck
    assert any("wedged" in "\n".join(s["frames"]) for s in stacks)
    # flight ring drained: the step's begin breadcrumb is the last beat
    begins = [l for l in lines if l.get("event") == "span_begin"]
    assert any(b["name"] == "TrainStep(Wedged)" for b in begins)
    # registry snapshot present
    metrics = [l for l in lines if l.get("event") == "metrics"]
    assert metrics and metrics[-1]["metrics"]["sentinel.metric"] == 5
    # the hang event reached the sinks too
    hangs = sink.events("hang")
    assert hangs and hangs[0]["postmortem"] == pm


def test_watchdog_enable_wiring_and_rearm(tmp_path):
    import time
    sink = obs.InMemorySink()
    pm = str(tmp_path / "wd.postmortem")
    hangs = []
    tel = obs.enable(sinks=[sink], crash_hooks=False, watchdog_s=0.25,
                     postmortem_path=pm, on_hang=hangs.append)
    try:
        assert tel.watchdog is not None and obs.get_watchdog() is tel.watchdog
        time.sleep(0.7)
        assert tel.watchdog.fired == 1     # stalled: exactly one dump
        assert hangs and hangs[0] is tel.watchdog
        with obs.span("progress"):          # beat: re-arms the watchdog
            pass
        time.sleep(0.6)
        assert tel.watchdog.fired == 2     # second stall, second dump
    finally:
        obs.disable()
    assert tel.watchdog._thread is None    # disable() stopped the thread
    assert os.path.exists(pm)


def test_enable_watchdog_requires_recorder_validates_first(telemetry):
    tel, sink = telemetry
    with pytest.raises(ValueError, match="flight recorder"):
        obs.enable(flight_recorder=False, watchdog_s=1.0)
    # validated BEFORE any side effect: the active session survives, no
    # extra compile listener / sink was created and leaked
    assert obs.get_telemetry() is tel


def test_watchdog_manual_beat_prevents_fire():
    import time
    wd = obs.HangWatchdog(deadline_s=0.3, poll_s=0.05,
                          recorder=obs.FlightRecorder())
    wd.start()
    try:
        for _ in range(10):
            time.sleep(0.1)
            wd.beat()
        assert wd.fired == 0
    finally:
        wd.stop()


# -- crash post-mortems ------------------------------------------------------

def test_write_postmortem_contents(tmp_path):
    from paddle_tpu.observability.flight_recorder import write_postmortem
    rec = obs.FlightRecorder(capacity=4)
    for i in range(6):
        rec.record("crumb", i=i)
    reg = obs.MetricsRegistry()
    reg.counter("c").inc(3)
    path = str(tmp_path / "pm.postmortem")
    out = write_postmortem(reason="test", path=path, recorder=rec,
                           registry_fn=reg.snapshot)
    assert out == path
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["reason"] == "test" and lines[0]["pid"] == os.getpid()
    meta = next(l for l in lines if l["event"] == "flight_recorder")
    assert meta["recorded"] == 4 and meta["total"] == 6
    crumbs = [l for l in lines if l["event"] == "crumb"]
    assert [c["i"] for c in crumbs] == [2, 3, 4, 5]   # last-N only
    assert lines[-1]["metrics"]["c"] == 3


_CRASH_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
import paddle_tpu.observability as obs
tel = obs.enable(jsonl_path={jsonl!r})
tel.emit({{"event": "custom", "marker": 23}})
{death}
"""


@pytest.mark.parametrize("death,reason,rc", [
    ("raise RuntimeError('boom')", "unhandled_exception", 1),
    ("sys.exit(7)", "atexit", 7),
])
def test_hard_exit_leaves_postmortem(tmp_path, death, reason, rc):
    """Acceptance: a run that dies mid-stream (unhandled exception, or a
    bare sys.exit) still leaves a readable .postmortem next to its JSONL
    — a killed run is never blind."""
    jsonl = str(tmp_path / "run.jsonl")
    r = subprocess.run(
        [sys.executable, "-c",
         _CRASH_SCRIPT.format(repo=REPO, jsonl=jsonl, death=death)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == rc, r.stderr
    pm = jsonl + ".postmortem"
    assert os.path.exists(pm)
    lines = [json.loads(l) for l in open(pm)]
    assert lines[0]["event"] == "postmortem"
    assert lines[0]["reason"] == reason
    if reason == "unhandled_exception":
        assert lines[0]["exception"]["message"] == "boom"
    kinds = [l["event"] for l in lines]
    assert "thread_stack" in kinds and "metrics" in kinds
    assert any(l.get("marker") == 23 for l in lines)
    # the post-mortem is itself a telemetry_report-readable stream
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--json", pm], capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    summary = json.loads(rep.stdout.strip().splitlines()[-1])
    assert summary["postmortems"] == [reason]
    assert summary["thread_stacks"] >= 1


def test_clean_disable_means_no_postmortem(tmp_path):
    """obs.disable() is the clean-shutdown signal: no dump on exit."""
    jsonl = str(tmp_path / "clean.jsonl")
    script = _CRASH_SCRIPT.format(repo=REPO, jsonl=jsonl,
                                  death="obs.disable()")
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, timeout=120,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    assert not os.path.exists(jsonl + ".postmortem")


# -- telemetry_report tool ---------------------------------------------------

def test_telemetry_report_folds_jsonl(tmp_path, telemetry):
    tel, sink = telemetry
    path = str(tmp_path / "run.jsonl")
    js = obs.JsonlSink(path)
    tel.sinks.append(js)
    step, state, batch = _tiny_trainstep()
    for _ in range(4):
        state, _ = step(state, batch)
    tel.flush()
    js.close()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         path], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| TrainStep(Linear) |" in r.stdout
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["sites"]["TrainStep(Linear)"]["steps"] == 4
    assert summary["compiles"]  # the TrainStep compile was attributed
    assert summary["malformed_lines"] == 0


def test_telemetry_report_truncated_and_malformed_lines(tmp_path):
    """A crash cuts the JSONL mid-line: the reporter must skip, COUNT,
    and report damaged lines — and still summarize what survived."""
    path = str(tmp_path / "cut.jsonl")
    good = {"event": "step", "site": "S", "step": 1, "wall_ms": 2.0,
            "interval_ms": 2.0, "warmup": False}
    with open(path, "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write(json.dumps({"event": "span", "name": "ckpt.save",
                            "ms": 3.25}) + "\n")
        cut = json.dumps({**good, "step": 2})
        f.write(cut[:len(cut) // 2] + "\n")     # crash-truncated line
        f.write("not json at all\n")            # garbage
        f.write("1234\n")                       # parses, but not an event
        f.write("\n")                           # blank: NOT damage
        f.write(json.dumps({**good, "step": 3}) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         path], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr           # must not raise
    assert "unparseable line skipped" in r.stderr
    assert "3 malformed/truncated line(s) skipped" in r.stdout
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["malformed_lines"] == 3
    assert summary["sites"]["S"]["steps"] == 2   # survivors summarized
    assert summary["spans"]["ckpt.save"]["n"] == 1


def test_telemetry_report_folds_serving_events(tmp_path):
    """The serve_* vocabulary (docs/SERVING.md) folds into a serving
    table + `serving` summary block — no engine needed, the reporter is
    pure stdlib over the event schema."""
    path = str(tmp_path / "serve.jsonl")
    with open(path, "w") as f:
        for i, (n, ct) in enumerate([(5, 0), (23, 16), (9, 8)]):
            f.write(json.dumps({"event": "serve_request", "id": f"r{i}",
                                "prompt_len": n, "slot": i, "blocks": 2,
                                "cached_tokens": ct}) + "\n")
        for ms, tok, act, q, sp in [(4.0, 1, 1, 2, 9), (2.0, 3, 3, 0, 3),
                                    (2.5, 3, 3, 0, 3), (3.0, 2, 2, 0, 2)]:
            f.write(json.dumps({"event": "serve_step", "ms": ms,
                                "tokens": tok, "active": act, "queue": q,
                                "span_tokens": sp,
                                "kv_blocks_used": 2 * act}) + "\n")
        f.write(json.dumps({"event": "serve_finish", "id": "r0",
                            "reason": "length", "tokens": 4,
                            "ms": 11.0}) + "\n")
        f.write(json.dumps({"event": "serve_finish", "id": "r1",
                            "reason": "eos", "tokens": 2,
                            "ms": 8.0}) + "\n")
        f.write(json.dumps({"event": "metrics", "metrics": {
            "serve.prefix_hits": 3, "serve.prefix_misses": 1,
            "serve.cow_copies": 1, "serve.shared_blocks": 2,
            "serve.cached_blocks": 4,
            "serve.ragged_occupancy": {"count": 4, "sum": 1.06,
                                       "p50": 0.19, "p95": 0.56}}}) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         path], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| Serving | |" in r.stdout
    assert "| requests (finished) | 3 (1 eos, 1 length) |" in r.stdout
    assert "| prefix pages hit / missed | 3 / 1 (0.750) |" in r.stdout
    assert "| prompt tokens from cache | 24 / 37 (0.649) |" in r.stdout
    assert "| CoW copies | 1 |" in r.stdout
    assert "| ragged occupancy p50 / p95 | 0.19 / 0.56 " \
           "(17 span tokens) |" in r.stdout
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    sv = summary["serving"]
    assert sv["requests"] == 3 and sv["steps"] == 4
    assert sv["tokens"] == 9
    assert sv["finished"] == {"eos": 1, "length": 1}
    assert sv["peak_active"] == 3 and sv["peak_queue"] == 2
    assert sv["peak_kv_blocks"] == 6
    assert sv["agg_tok_s"] == round(9 / (11.5 / 1e3), 1)
    assert sv["prefix_hits"] == 3 and sv["prefix_hit_rate"] == 0.75
    assert sv["cached_tokens"] == 24 and sv["span_tokens"] == 17
    assert sv["cow_copies"] == 1 and sv["shared_blocks"] == 2
    assert sv["cached_blocks"] == 4
    assert sv["ragged_occupancy_p95"] == 0.56


def test_telemetry_report_json_only_mode_counts_malformed(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write('{"event":"step","site":"S","wall_\n')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--json", path], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["events"] == 0 and summary["malformed_lines"] == 1
