"""Resilience subsystem tests: deterministic fault injection, retry with
backoff, checkpoint integrity + last-good fallback, and the auto-resuming
supervisor (docs/RESILIENCE.md).

The load-bearing property throughout: a supervised run with injected
faults must reproduce the fault-free run EXACTLY — recovery that loses or
replays work incorrectly is worse than a crash (it corrupts training
silently).  The chaos CI gate (tools/ci.py --only chaos) asserts the same
contract end-to-end in a fresh process."""

import os
import signal as sig
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu import ckpt, nn, optimizer
from paddle_tpu import resilience as rs
from paddle_tpu.jit import TrainStep


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_compile_cache():
    """Bitwise-reproducibility tests must not mix persistent-cache
    DESERIALIZED executables with fresh compiles: on this jax/XLA the two
    can differ numerically (and a torn cache entry can crash outright) —
    the same reason the chaos gate runs uncached.  Compiles here are tiny
    Linear(4,4) programs; caching buys nothing."""
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", True)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    rs.clear_faults()


def _make_step():
    pt.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    return TrainStep(m, lambda mm, b: ((mm(b["x"]) - b["y"]) ** 2).mean(),
                     opt)


def _batch_of(i):
    r = np.random.default_rng(i)   # batch = f(step index): replayable
    return {"x": jnp.asarray(r.normal(size=(4, 4)), jnp.float32),
            "y": jnp.asarray(r.normal(size=(4, 4)), jnp.float32)}


def _params_bytes(state):
    return b"".join(np.asarray(l).tobytes()
                    for l in jax.tree_util.tree_leaves(state["params"]))


_NOSLEEP = dict(backoff_s=0.0, jitter=0.0, sleep=lambda _s: None)


class TestFaultSpec:
    def test_parse_grammar(self):
        plans = rs.parse_faults("ckpt.save@1, step@3x2:OSError; store.get@0")
        assert [(p.site, p.at, p.times) for p in plans] == [
            ("ckpt.save", 1, 1), ("step", 3, 2), ("store.get", 0, 1)]
        assert plans[1].exc is OSError
        assert plans[0].exc is rs.InjectedFault

    def test_bad_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            rs.parse_faults("nope@0")

    def test_bad_exc_rejected(self):
        # only a whitelist of exception names — an env var must not be
        # able to name arbitrary types (and SystemExit would skip every
        # recovery path)
        with pytest.raises(ValueError, match="unknown fault exception"):
            rs.parse_faults("step@0:SystemExit")

    def test_bad_entry_rejected(self):
        with pytest.raises(ValueError, match="grammar"):
            rs.parse_faults("step")

    def test_injector_counts_and_fires(self):
        inj = rs.install_faults("step@1x2")
        inj("step")                      # call 0: passes
        for _ in range(2):               # calls 1-2: planned window
            with pytest.raises(rs.InjectedFault):
                inj("step")
        inj("step")                      # call 3: plan exhausted
        assert inj.calls("step") == 4
        assert inj.fired == [("step", 1), ("step", 2)]

    def test_env_install_and_no_clobber(self, monkeypatch):
        monkeypatch.setenv("PDTPU_FAULTS", "collective@0")
        inj = rs.install_faults_from_env()
        assert inj is rs.active_injector()
        with pytest.raises(rs.InjectedFault):
            inj("collective")
        # a code-configured injector is never clobbered by the env spec
        assert rs.install_faults_from_env() is inj
        rs.clear_faults()
        assert rs.active_injector() is None


class TestRetryPolicy:
    def test_retries_then_succeeds(self):
        sleeps = []
        p = rs.RetryPolicy(max_attempts=3, backoff_s=0.01, multiplier=2.0,
                           jitter=0.0, sleep=sleeps.append)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise ConnectionError("blip")
            return 42

        assert p.run(flaky, site="t") == 42
        assert calls[0] == 3 and len(sleeps) == 2
        assert sleeps[1] == pytest.approx(2 * sleeps[0])   # exponential

    def test_gives_up_and_reraises_original(self):
        p = rs.RetryPolicy(max_attempts=2, **_NOSLEEP)
        with pytest.raises(OSError, match="disk"):
            p.run(lambda: (_ for _ in ()).throw(OSError("disk")), site="t")

    def test_non_retryable_raises_immediately(self):
        sleeps = []
        p = rs.RetryPolicy(max_attempts=5, backoff_s=0.0, jitter=0.0,
                           sleep=sleeps.append)
        with pytest.raises(ValueError):
            p.run(lambda: (_ for _ in ()).throw(ValueError("logic")))
        assert sleeps == []   # never slept: not a transient

    def test_jitter_deterministic_and_bounded(self):
        p = rs.RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=0.5,
                           jitter=0.25)
        assert p.delay_s(1, "x") == p.delay_s(1, "x")   # no RNG anywhere
        for attempt in range(1, 8):
            d = p.delay_s(attempt, "x")
            assert 0.0 < d <= 0.5 * 1.25   # capped base * (1 + jitter)

    def test_retry_events_and_counters(self):
        import paddle_tpu.observability as obs
        sink = obs.InMemorySink()
        obs.enable(sinks=[sink], crash_hooks=False)
        try:
            p = rs.RetryPolicy(max_attempts=3, **_NOSLEEP)
            calls = [0]

            def flaky():
                calls[0] += 1
                if calls[0] < 2:
                    raise TimeoutError("slow store")
                return "ok"

            assert p.run(flaky, site="store.get") == "ok"
            evs = sink.events("retry")
            assert len(evs) == 1
            ev = evs[0]
            assert ev["site"] == "store.get" and ev["attempt"] == 1
            assert ev["exc"] == "TimeoutError" and "delay_s" in ev
            reg = obs.get_registry()
            assert reg.counter("retry[store.get].count").value == 1
            # the event also landed in the flight-recorder ring
            ring = [e for e in obs.get_flight_recorder().snapshot()
                    if e.get("event") == "retry"]
            assert ring
        finally:
            obs.disable()


class TestStoreResilience:
    def test_store_ops_survive_injected_faults(self):
        from paddle_tpu.launch import TCPStore
        from paddle_tpu.launch.store import free_port
        rs.install_faults("store.set@0,store.get@0")
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True,
                     retry=rs.RetryPolicy(max_attempts=3, **_NOSLEEP))
        try:
            s.set("k", b"v")
            assert s.get("k") == b"v"
            inj = rs.active_injector()
            assert {f[0] for f in inj.fired} == {"store.set", "store.get"}
        finally:
            s.close()

    def test_store_without_policy_raises(self):
        from paddle_tpu.launch import TCPStore
        from paddle_tpu.launch.store import free_port
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            rs.install_faults("store.set@0")
            with pytest.raises(rs.InjectedFault):
                s.set("k", b"v")
            rs.clear_faults()
            s.set("k", b"v")          # the store itself is still healthy
            assert s.get("k") == b"v"
        finally:
            s.close()


class TestCkptIntegrity:
    def test_checksums_recorded(self, tmp_path):
        import json
        d = str(tmp_path / "ck")
        ckpt.save_state_dict({"w": np.arange(8.0, dtype=np.float32)}, d)
        meta = json.load(open(os.path.join(d, "metadata.json")))
        files = meta["arrays"]["w"]["files"]
        assert all("crc32" in f and "nbytes" in f for f in files)
        assert os.path.exists(os.path.join(d, "COMMITTED"))
        assert ckpt.verify_checkpoint(d) == []

    def test_corrupt_shard_raises_and_verify_false_skips(self, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save_state_dict({"w": np.arange(8.0, dtype=np.float32)}, d)
        shard = next(f for f in os.listdir(d) if f.endswith(".npy"))
        p = os.path.join(d, shard)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        with pytest.raises(ckpt.CheckpointCorruptError, match="checksum"):
            ckpt.load_state_dict(d)
        assert ckpt.verify_checkpoint(d)          # non-empty problem list
        ckpt.load_state_dict(d, verify=False)     # opt-out still reads

    def test_missing_commit_sentinel_means_incomplete(self, tmp_path):
        root = str(tmp_path)
        d = os.path.join(root, "step_5")
        ckpt.save_state_dict({"w": np.ones(2)}, d)
        assert ckpt.latest_checkpoint(root) == d
        os.remove(os.path.join(d, "COMMITTED"))
        # a v2 directory without its sentinel is a torn save
        assert ckpt.latest_checkpoint(root) is None
        assert ckpt.verify_checkpoint(d)

    def test_latest_valid_only_falls_back_past_corruption(self, tmp_path):
        root = str(tmp_path)
        for n in (2, 4):
            ckpt.save_state_dict({"w": np.full(4, float(n))},
                                 os.path.join(root, f"step_{n}"))
        newest = os.path.join(root, "step_4")
        shard = next(f for f in os.listdir(newest) if f.endswith(".npy"))
        p = os.path.join(newest, shard)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        # default: newest complete dir (corruption unseen without reads)
        assert ckpt.latest_checkpoint(root) == newest
        # valid_only: data-verified, falls back to the last GOOD one
        assert ckpt.latest_checkpoint(root, valid_only=True) == \
            os.path.join(root, "step_2")

    def test_resave_overwrite_false_keeps_checksums(self, tmp_path):
        """A re-save that reuses existing shard files (overwrite=False)
        replaces the metadata — it must re-checksum the reused files, not
        silently drop corruption detection for them."""
        import json
        d = str(tmp_path / "ck")
        ckpt.save_state_dict({"w": np.arange(4.0)}, d)
        ckpt.save_state_dict({"w": np.arange(4.0)}, d, overwrite=False)
        meta = json.load(open(os.path.join(d, "metadata.json")))
        assert all("crc32" in f for f in meta["arrays"]["w"]["files"])
        shard = next(f for f in os.listdir(d) if f.endswith(".npy"))
        p = os.path.join(d, shard)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.load_state_dict(d)

    def test_verify_checkpoint_reports_missing_shard(self, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save_state_dict({"w": np.ones(4)}, d)
        shard = next(f for f in os.listdir(d) if f.endswith(".npy"))
        os.remove(os.path.join(d, shard))
        assert any("missing shard" in p for p in ckpt.verify_checkpoint(d))

    def test_save_unlinks_tmp_on_failed_write(self, tmp_path, monkeypatch):
        import pickle as _pickle
        path = str(tmp_path / "m.pd")

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(_pickle, "dump", boom)
        with pytest.raises(OSError):
            ckpt.save({"w": np.ones(2)}, path)
        monkeypatch.undo()
        assert not os.path.exists(path + ".tmp")   # no debris
        ckpt.save({"w": np.ones(2)}, path)         # clean retry-by-hand
        np.testing.assert_array_equal(
            np.asarray(ckpt.load(path)["w"]), np.ones(2))

    def test_write_entries_unlinks_metadata_tmp_on_failure(self, tmp_path,
                                                           monkeypatch):
        import json as _json
        d = str(tmp_path / "ck")

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(_json, "dump", boom)
        with pytest.raises(OSError):
            ckpt.save_state_dict({"w": np.ones(2)}, d)
        monkeypatch.undo()
        assert not [f for f in os.listdir(d) if ".tmp" in f]
        ckpt.save_state_dict({"w": np.ones(2)}, d)   # debris-free re-save
        assert ckpt.verify_checkpoint(d) == []

    def test_ckpt_retry_absorbs_injected_faults(self, tmp_path):
        d = str(tmp_path / "ck")
        pol = rs.RetryPolicy(max_attempts=3, **_NOSLEEP)
        rs.install_faults("ckpt.save@0,ckpt.load@0")
        ckpt.save_state_dict({"w": np.arange(3.0)}, d, retry=pol)
        out = ckpt.load_state_dict(d, retry=pol)
        np.testing.assert_array_equal(out["w"], np.arange(3.0))
        inj = rs.active_injector()
        assert {f[0] for f in inj.fired} == {"ckpt.save", "ckpt.load"}


class TestSupervisor:
    def _run(self, ckpt_dir, num_steps=4, faults=None, calls=None,
             max_attempts=4, guard=None):
        rs.clear_faults()
        if faults:
            rs.install_faults(faults)
        step = _make_step()

        def step_fn(state, i):
            if calls is not None:
                calls.append(i)
            st, _ = step(state, _batch_of(i))
            return st

        pol = rs.RetryPolicy(max_attempts=max_attempts, **_NOSLEEP)
        final = rs.run_resilient(step_fn, state=step.init_state(),
                                 num_steps=num_steps, ckpt_dir=ckpt_dir,
                                 policy=pol, save_every=2, guard=guard)
        return final

    def test_fault_free_supervised_run_matches_plain_loop(self, tmp_path):
        final = self._run(str(tmp_path / "ck"))
        step = _make_step()
        st = step.init_state()
        for i in range(4):
            st, _ = step(st, _batch_of(i))
        assert _params_bytes(final) == _params_bytes(st)

    def test_resume_after_step_fault_bitwise(self, tmp_path):
        p0 = _params_bytes(self._run(str(tmp_path / "a")))
        calls = []
        p1 = _params_bytes(self._run(str(tmp_path / "b"), faults="step@3",
                                     calls=calls))
        assert p1 == p0
        # the fault hit at i=3; the restart restored step_2 and replayed
        # steps 2..3 — the call log shows the replay, not silent skips
        assert calls == [0, 1, 2, 3, 2, 3]
        assert rs.active_injector().fired == [("step", 3)]

    def test_restart_bound_exhausts(self, tmp_path):
        with pytest.raises(rs.InjectedFault):
            self._run(str(tmp_path / "ck"), faults="step@0x99",
                      max_attempts=2)

    def test_non_retryable_step_error_propagates(self, tmp_path):
        step = _make_step()

        def bad_step(state, i):
            raise ValueError("logic bug, not a transient")

        pol = rs.RetryPolicy(max_attempts=5, **_NOSLEEP)
        with pytest.raises(ValueError, match="logic bug"):
            rs.run_resilient(bad_step, state=step.init_state(), num_steps=2,
                             ckpt_dir=str(tmp_path / "ck"), policy=pol)

    def test_corrupted_newest_falls_back_and_reproduces(self, tmp_path):
        d = str(tmp_path / "ck")
        p0 = _params_bytes(self._run(d))
        newest = ckpt.latest_checkpoint(d)
        assert newest.endswith("step_4")
        shard = next(f for f in sorted(os.listdir(newest))
                     if f.endswith(".npy"))
        p = os.path.join(newest, shard)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        # re-running the same job restores step_2 and replays to the end,
        # reproducing the original params despite the torn newest ckpt
        assert _params_bytes(self._run(d)) == p0

    def test_preemption_guard_cooperation(self, tmp_path):
        from paddle_tpu.launch import PreemptionGuard
        d = str(tmp_path / "ck")
        calls = []
        with PreemptionGuard() as guard:
            os.kill(os.getpid(), sig.SIGTERM)   # preempt before the loop
            time.sleep(0.05)
            assert guard.preempted
            self._run(d, calls=calls, guard=guard)
        # supervisor stopped at the preemption check: no steps ran, and a
        # resumable checkpoint exists at the stop point
        assert calls == []
        assert ckpt.latest_checkpoint(d, valid_only=True) is not None

    def test_resume_restart_events_emitted(self, tmp_path):
        import paddle_tpu.observability as obs
        sink = obs.InMemorySink()
        obs.enable(sinks=[sink], crash_hooks=False)
        try:
            self._run(str(tmp_path / "ck"), faults="step@3")
            kinds = [e.get("event") for e in sink.events()]
            assert "fault" in kinds and "restart" in kinds \
                and "resume" in kinds
            resume = sink.events("resume")[0]
            assert resume["step"] == 2 and resume["restarts"] == 1
            reg = obs.get_registry()
            assert reg.counter("resilience.restarts").value == 1
        finally:
            obs.disable()

    def test_keep_prunes_but_retains_fallback(self, tmp_path):
        d = str(tmp_path / "ck")
        step = _make_step()
        pol = rs.RetryPolicy(max_attempts=2, **_NOSLEEP)
        rs.run_resilient(lambda st, i: step(st, _batch_of(i))[0],
                         state=step.init_state(), num_steps=6,
                         ckpt_dir=d, policy=pol, save_every=1, keep=2)
        names = sorted(os.listdir(d))
        assert names == ["step_5", "step_6"]
        with pytest.raises(ValueError, match="keep"):
            rs.Supervisor(d, keep=1)


class TestFitResilient:
    def _batches(self, n=6):
        out = []
        for i in range(n):
            r = np.random.default_rng(100 + i)
            out.append((jnp.asarray(r.normal(size=(4, 4)), jnp.float32),
                        jnp.asarray(r.normal(size=(4, 4)), jnp.float32)))
        return out

    def _hapi_model(self):
        from paddle_tpu.hapi import Model
        pt.seed(0)
        net = nn.Linear(4, 4)
        model = Model(net)
        model.prepare(
            optimizer.AdamW(learning_rate=1e-2, parameters=net.parameters()),
            lambda pred, label: ((pred - label) ** 2).mean())
        return model

    def test_hapi_fit_resumes_bitwise(self, tmp_path):
        batches = self._batches()
        pol = rs.RetryPolicy(max_attempts=4, **_NOSLEEP)

        def fit(d, faults=None):
            rs.clear_faults()
            if faults:
                rs.install_faults(faults)
            model = self._hapi_model()
            metrics = rs.run_resilient(model, train_data=batches, epochs=1,
                                       ckpt_dir=d, policy=pol, save_every=2)
            return _params_bytes(model._state), metrics

        p0, m0 = fit(str(tmp_path / "a"))
        p1, m1 = fit(str(tmp_path / "b"), faults="step@3")
        assert p1 == p0
        assert m1["loss"] == pytest.approx(m0["loss"])
        assert rs.active_injector().fired == [("step", 3)]

    def test_engine_fit_resumes_bitwise(self, tmp_path):
        from paddle_tpu import distributed as dist
        batches = [{"x": x, "y": y} for x, y in self._batches()]
        pol = rs.RetryPolicy(max_attempts=4, **_NOSLEEP)

        def fit(d, faults=None):
            rs.clear_faults()
            if faults:
                rs.install_faults(faults)
            pt.seed(0)
            m = nn.Linear(4, 4)
            eng = dist.Engine(
                m, loss=lambda mm, b: ((mm(b["x"]) - b["y"]) ** 2).mean(),
                optimizer=optimizer.AdamW(learning_rate=1e-2,
                                          parameters=m.parameters()))
            rs.run_resilient(eng, train_data=batches, epochs=1,
                             ckpt_dir=d, policy=pol, save_every=2)
            return _params_bytes(eng.state)

        p0 = fit(str(tmp_path / "a"))
        p1 = fit(str(tmp_path / "b"), faults="step@2")
        assert p1 == p0

    def test_rerun_after_completion_is_stable(self, tmp_path):
        # re-invoking a finished supervised fit resumes past the end and
        # must not retrain or corrupt the checkpoints
        batches = self._batches(4)
        pol = rs.RetryPolicy(max_attempts=2, **_NOSLEEP)
        d = str(tmp_path / "ck")
        model = self._hapi_model()
        rs.run_resilient(model, train_data=batches, epochs=1,
                         ckpt_dir=d, policy=pol, save_every=2)
        p0 = _params_bytes(model._state)
        model2 = self._hapi_model()
        rs.run_resilient(model2, train_data=batches, epochs=1,
                         ckpt_dir=d, policy=pol, save_every=2)
        assert _params_bytes(model2._state) == p0
