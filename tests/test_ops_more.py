"""NumPy-oracle tests for the breadth ops (reference pattern: OpTest
compares kernel output against a NumPy reference impl — SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu as pt

R = np.random.default_rng(7)


def A(*shape, dtype="float32"):
    return R.normal(size=shape).astype(dtype)


class TestNanReductions:
    def test_nansum_mean_median(self):
        x = A(4, 5)
        x[1, 2] = np.nan
        np.testing.assert_allclose(pt.nansum(x), np.nansum(x), rtol=1e-6)
        np.testing.assert_allclose(pt.nanmean(x), np.nanmean(x), rtol=1e-6)
        np.testing.assert_allclose(pt.nanmedian(x), np.nanmedian(x), rtol=1e-6)

    def test_quantile(self):
        x = A(64)
        np.testing.assert_allclose(pt.quantile(x, 0.25),
                                   np.quantile(x, 0.25), rtol=1e-5)
        np.testing.assert_allclose(
            pt.nanquantile(x, [0.1, 0.9]), np.nanquantile(x, [0.1, 0.9]),
            rtol=1e-5)

    def test_nansum_keepdim_and_weighted_histogram(self):
        x = A(3, 4)
        assert pt.nansum(x, axis=0, keepdim=True).shape == (1, 4)
        assert pt.nanmean(x, axis=1, keepdim=True).shape == (3, 1)
        w = np.abs(A(3, 4))
        got = pt.histogram(pt.to_tensor(x), bins=4, min=-2, max=2,
                           weight=pt.to_tensor(w))
        want, _ = np.histogram(x.reshape(-1), bins=4, range=(-2, 2),
                               weights=w.reshape(-1))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_histogram(self):
        x = A(100)
        got = pt.histogram(x, bins=10, min=-2, max=2)
        want, _ = np.histogram(x, bins=10, range=(-2, 2))
        np.testing.assert_array_equal(np.asarray(got), want)
        # min==max==0 → data range
        got = pt.histogram(x, bins=5)
        want, _ = np.histogram(x, bins=5, range=(x.min(), x.max()))
        np.testing.assert_array_equal(np.asarray(got), want)


class TestCumMaxMin:
    def test_cummax_values_and_indices(self):
        x = np.array([[1.0, 3.0, 2.0, 5.0, 4.0]], np.float32)
        v, i = pt.cummax(x, axis=1)
        np.testing.assert_allclose(np.asarray(v),
                                   np.maximum.accumulate(x, 1))
        np.testing.assert_array_equal(np.asarray(i), [[0, 1, 1, 3, 3]])

    def test_cummin(self):
        x = A(3, 6)
        v, _ = pt.cummin(x, axis=1)
        np.testing.assert_allclose(np.asarray(v),
                                   np.minimum.accumulate(x, 1), rtol=1e-6)


class TestManipulation:
    def test_meshgrid(self):
        a, b = np.arange(3.0), np.arange(4.0)
        got = pt.meshgrid(a, b)
        want = np.meshgrid(a, b, indexing="ij")
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)

    def test_split_family(self):
        x = A(6, 4, 2)
        for got, want in zip(pt.tensor_split(x, 3), np.array_split(x, 3)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.vsplit(x, 2), np.vsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.hsplit(x, 2), np.hsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.dsplit(x, 2), np.dsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)

    def test_unflatten_take_expand_as_unstack(self):
        x = A(2, 12)
        assert pt.unflatten(x, 1, (3, 4)).shape == (2, 3, 4)
        idx = np.array([[0, 5], [23, -1]])
        got = pt.take(pt.to_tensor(x), pt.to_tensor(idx))
        # paddle take: negative indices count from the end (unlike
        # np.take(mode="clip"), which clips them to 0)
        flat = x.reshape(-1)
        want = flat[np.array([[0, 5], [23, 23]])]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
        y = A(3, 2, 12)
        assert pt.expand_as(x, y).shape == (3, 2, 12)
        parts = pt.unstack(pt.to_tensor(y), axis=1)
        assert len(parts) == 2 and parts[0].shape == (3, 12)

    def test_diag_embed_diagflat_indices(self):
        v = A(2, 3)
        out = np.asarray(pt.diag_embed(v))
        assert out.shape == (2, 3, 3)
        np.testing.assert_allclose(out[0], np.diag(v[0]), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(pt.diagflat(v[0])),
                                      np.diagflat(v[0]))
        np.testing.assert_array_equal(
            np.asarray(pt.tril_indices(4, 4)), np.stack(np.tril_indices(4)))

    def test_rot90_blockdiag_bucketize(self):
        x = A(3, 4)
        np.testing.assert_array_equal(np.asarray(pt.rot90(x)), np.rot90(x))
        got = np.asarray(pt.block_diag([np.eye(2), np.ones((1, 3))]))
        assert got.shape == (3, 5)
        edges = np.array([0.0, 1.0, 2.0])
        vals = np.array([-0.5, 0.5, 1.5, 2.5])
        np.testing.assert_array_equal(np.asarray(pt.bucketize(vals, edges)),
                                      np.searchsorted(edges, vals))

    def test_crop_unfold_as_strided(self):
        x = A(4, 6)
        got = np.asarray(pt.crop(x, shape=[2, -1], offsets=[1, 2]))
        np.testing.assert_array_equal(got, x[1:3, 2:])
        w = np.asarray(pt.unfold(pt.to_tensor(np.arange(10.0)), 0, 4, 3))
        np.testing.assert_array_equal(w, [[0, 1, 2, 3], [3, 4, 5, 6],
                                          [6, 7, 8, 9]])
        # non-last axis: window dim must land LAST (paddle/torch convention)
        m = A(10, 2)
        w2 = np.asarray(pt.unfold(pt.to_tensor(m), 0, 4, 3))
        assert w2.shape == (3, 2, 4)
        np.testing.assert_allclose(w2[1, 0], m[3:7, 0], rtol=1e-6)
        s = np.asarray(pt.as_strided(pt.to_tensor(np.arange(12.0)),
                                     (3, 2), (4, 1)))
        np.testing.assert_array_equal(
            s, np.lib.stride_tricks.as_strided(
                np.arange(12.0), (3, 2), (32, 8)))


class TestComplexViews:
    def test_complex_roundtrip(self):
        x = A(3, 2)
        c = pt.as_complex(pt.to_tensor(x))
        np.testing.assert_allclose(np.asarray(pt.real(c)), x[:, 0], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.imag(c)), x[:, 1], rtol=1e-6)
        back = np.asarray(pt.as_real(c))
        np.testing.assert_allclose(back, x, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.angle(c)),
                                   np.angle(x[:, 0] + 1j * x[:, 1]), rtol=1e-5)


class TestMiscMath:
    def test_pointwise_oracle(self):
        x = np.abs(A(16)) + 0.1
        y = A(16)
        np.testing.assert_allclose(np.asarray(pt.heaviside(y, x)),
                                   np.heaviside(y, x), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.copysign(x, y)),
                                   np.copysign(x, y), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.frac(y * 3)),
                                   (y * 3) - np.trunc(y * 3), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(pt.deg2rad(x)),
                                   np.deg2rad(x), rtol=1e-6)
        a = np.array([4, 6, 9]); b = np.array([6, 4, 6])
        np.testing.assert_array_equal(np.asarray(pt.gcd(a, b)), np.gcd(a, b))
        np.testing.assert_array_equal(np.asarray(pt.lcm(a, b)), np.lcm(a, b))

    def test_trapezoid_vander(self):
        y = A(9)
        np.testing.assert_allclose(np.asarray(pt.trapezoid(y, dx=0.5)),
                                   np.trapezoid(y, dx=0.5), rtol=1e-5)
        v = A(4)
        np.testing.assert_allclose(np.asarray(pt.vander(v, 3)),
                                   np.vander(v, 3), rtol=1e-5)

    def test_renorm_multiplex_indexput_clipnorm(self):
        x = A(3, 4)
        out = np.asarray(pt.renorm(x, 2.0, 0, 1.0))
        norms = np.linalg.norm(out.reshape(3, -1), axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        cands = [A(4, 2), A(4, 2)]
        idx = np.array([0, 1, 1, 0])
        got = np.asarray(pt.multiplex(
            [pt.to_tensor(c) for c in cands], pt.to_tensor(idx)))
        want = np.stack([cands[idx[i]][i] for i in range(4)])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        z = np.zeros((3, 3), np.float32)
        got = np.asarray(pt.index_put(pt.to_tensor(z),
                                      (np.array([0, 2]), np.array([1, 2])),
                                      np.array([5.0, 7.0], np.float32)))
        assert got[0, 1] == 5 and got[2, 2] == 7
        big = np.ones(8, np.float32) * 10
        clipped = np.asarray(pt.clip_by_norm(pt.to_tensor(big), 1.0))
        np.testing.assert_allclose(np.linalg.norm(clipped), 1.0, rtol=1e-5)

    def test_special_functions(self):
        x = np.abs(A(8)) + 0.5
        import scipy.special as ss
        pytest.importorskip("scipy")
        np.testing.assert_allclose(np.asarray(pt.i0(x)), ss.i0(x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.polygamma(x, 1)),
                                   ss.polygamma(1, x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.gammainc(x, x)),
                                   ss.gammainc(x, x), rtol=1e-4)

    def test_sgn_complex(self):
        c = np.array([3 + 4j, 0 + 0j], np.complex64)
        got = np.asarray(pt.sgn(pt.to_tensor(c)))
        np.testing.assert_allclose(got[0], 0.6 + 0.8j, rtol=1e-5)
        assert got[1] == 0


class TestLinalgExtras:
    def test_triangular_and_cholesky_solve(self):
        a = A(4, 4)
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        b = A(4, 2)
        lo = np.linalg.cholesky(spd).astype("float32")
        got = np.asarray(pt.ops.linalg.triangular_solve(lo.T, b, upper=True))
        want = np.linalg.solve(lo.T, b)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
        got = np.asarray(pt.ops.linalg.cholesky_solve(b, lo, upper=False))
        np.testing.assert_allclose(got, np.linalg.solve(spd, b),
                                   rtol=1e-3, atol=1e-4)

    def test_lu_packed_convention(self):
        import scipy.linalg as sl
        a = A(4, 4) + 4 * np.eye(4, dtype="float32")
        lu, piv = pt.ops.linalg.lu(a)
        want_lu, want_piv = sl.lu_factor(a)
        np.testing.assert_allclose(np.asarray(lu), want_lu, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(piv), want_piv + 1)  # 1-based
        lu2, piv2, infos = pt.ops.linalg.lu(a, get_infos=True)
        assert infos.shape == () and int(infos) == 0

    def test_cov_corrcoef_expm(self):
        x = A(3, 50)
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.cov(x)),
                                   np.cov(x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.corrcoef(x)),
                                   np.corrcoef(x), rtol=1e-4)
        m = A(3, 3) * 0.1
        import scipy.linalg as sl
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.matrix_exp(m)),
                                   sl.expm(m), rtol=1e-4, atol=1e-5)

    def test_fft_extras(self):
        x = A(8)
        np.testing.assert_allclose(np.asarray(pt.ops.fft.hfft(x)),
                                   np.fft.hfft(x), rtol=1e-4, atol=1e-4)
        c = A(4, 4)
        np.testing.assert_allclose(np.asarray(pt.ops.fft.rfftn(c)),
                                   np.fft.rfftn(c), rtol=1e-4, atol=1e-4)


class TestDistanceAndScatterNd:
    def test_scatter_nd(self):
        index = np.array([[1], [2], [1]], np.int64)
        updates = np.array([9.0, 10.0, 11.0], np.float32)
        out = np.asarray(pt.scatter_nd(index, updates, [4]))
        # duplicates accumulate (paddle.scatter_nd semantics)
        np.testing.assert_allclose(out, [0.0, 20.0, 10.0, 0.0])

    def test_scatter_nd_2d_index(self):
        index = np.array([[0, 1], [2, 3]], np.int64)
        updates = A(2, 5)
        out = np.asarray(pt.scatter_nd(index, updates, [3, 4, 5]))
        expect = np.zeros((3, 4, 5), np.float32)
        expect[0, 1] += updates[0]
        expect[2, 3] += updates[1]
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    @pytest.mark.parametrize("p", [0.0, 1.0, 2.0, 3.0, float("inf")])
    def test_cdist_vs_torch(self, p):
        import torch
        x, y = A(2, 5, 4), A(2, 7, 4)
        ours = np.asarray(pt.cdist(x, y, p=p))
        ref = torch.cdist(torch.tensor(x), torch.tensor(y), p=p).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_cdist_no_mm_matches_mm(self):
        x, y = A(3, 4), A(5, 4)
        mm = np.asarray(pt.cdist(x, y))
        no_mm = np.asarray(
            pt.cdist(x, y, compute_mode="donot_use_mm_for_euclid_dist"))
        np.testing.assert_allclose(mm, no_mm, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("p", [1.0, 2.0])
    def test_pdist_vs_torch(self, p):
        import torch
        x = A(6, 3)
        ours = np.asarray(pt.pdist(x, p=p))
        ref = torch.pdist(torch.tensor(x), p=p).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
