"""NumPy-oracle tests for the breadth ops (reference pattern: OpTest
compares kernel output against a NumPy reference impl — SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu as pt

R = np.random.default_rng(7)


def A(*shape, dtype="float32"):
    return R.normal(size=shape).astype(dtype)


class TestNanReductions:
    def test_nansum_mean_median(self):
        x = A(4, 5)
        x[1, 2] = np.nan
        np.testing.assert_allclose(pt.nansum(x), np.nansum(x), rtol=1e-6)
        np.testing.assert_allclose(pt.nanmean(x), np.nanmean(x), rtol=1e-6)
        np.testing.assert_allclose(pt.nanmedian(x), np.nanmedian(x), rtol=1e-6)

    def test_quantile(self):
        x = A(64)
        np.testing.assert_allclose(pt.quantile(x, 0.25),
                                   np.quantile(x, 0.25), rtol=1e-5)
        np.testing.assert_allclose(
            pt.nanquantile(x, [0.1, 0.9]), np.nanquantile(x, [0.1, 0.9]),
            rtol=1e-5)

    def test_nansum_keepdim_and_weighted_histogram(self):
        x = A(3, 4)
        assert pt.nansum(x, axis=0, keepdim=True).shape == (1, 4)
        assert pt.nanmean(x, axis=1, keepdim=True).shape == (3, 1)
        w = np.abs(A(3, 4))
        got = pt.histogram(pt.to_tensor(x), bins=4, min=-2, max=2,
                           weight=pt.to_tensor(w))
        want, _ = np.histogram(x.reshape(-1), bins=4, range=(-2, 2),
                               weights=w.reshape(-1))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_histogram(self):
        x = A(100)
        got = pt.histogram(x, bins=10, min=-2, max=2)
        want, _ = np.histogram(x, bins=10, range=(-2, 2))
        np.testing.assert_array_equal(np.asarray(got), want)
        # min==max==0 → data range
        got = pt.histogram(x, bins=5)
        want, _ = np.histogram(x, bins=5, range=(x.min(), x.max()))
        np.testing.assert_array_equal(np.asarray(got), want)


class TestCumMaxMin:
    def test_cummax_values_and_indices(self):
        x = np.array([[1.0, 3.0, 2.0, 5.0, 4.0]], np.float32)
        v, i = pt.cummax(x, axis=1)
        np.testing.assert_allclose(np.asarray(v),
                                   np.maximum.accumulate(x, 1))
        np.testing.assert_array_equal(np.asarray(i), [[0, 1, 1, 3, 3]])

    def test_cummin(self):
        x = A(3, 6)
        v, _ = pt.cummin(x, axis=1)
        np.testing.assert_allclose(np.asarray(v),
                                   np.minimum.accumulate(x, 1), rtol=1e-6)


class TestManipulation:
    def test_meshgrid(self):
        a, b = np.arange(3.0), np.arange(4.0)
        got = pt.meshgrid(a, b)
        want = np.meshgrid(a, b, indexing="ij")
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)

    def test_split_family(self):
        x = A(6, 4, 2)
        for got, want in zip(pt.tensor_split(x, 3), np.array_split(x, 3)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.vsplit(x, 2), np.vsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.hsplit(x, 2), np.hsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(pt.dsplit(x, 2), np.dsplit(x, 2)):
            np.testing.assert_array_equal(np.asarray(got), want)

    def test_unflatten_take_expand_as_unstack(self):
        x = A(2, 12)
        assert pt.unflatten(x, 1, (3, 4)).shape == (2, 3, 4)
        idx = np.array([[0, 5], [23, -1]])
        got = pt.take(pt.to_tensor(x), pt.to_tensor(idx))
        # paddle take: negative indices count from the end (unlike
        # np.take(mode="clip"), which clips them to 0)
        flat = x.reshape(-1)
        want = flat[np.array([[0, 5], [23, 23]])]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
        y = A(3, 2, 12)
        assert pt.expand_as(x, y).shape == (3, 2, 12)
        parts = pt.unstack(pt.to_tensor(y), axis=1)
        assert len(parts) == 2 and parts[0].shape == (3, 12)

    def test_diag_embed_diagflat_indices(self):
        v = A(2, 3)
        out = np.asarray(pt.diag_embed(v))
        assert out.shape == (2, 3, 3)
        np.testing.assert_allclose(out[0], np.diag(v[0]), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(pt.diagflat(v[0])),
                                      np.diagflat(v[0]))
        np.testing.assert_array_equal(
            np.asarray(pt.tril_indices(4, 4)), np.stack(np.tril_indices(4)))

    def test_rot90_blockdiag_bucketize(self):
        x = A(3, 4)
        np.testing.assert_array_equal(np.asarray(pt.rot90(x)), np.rot90(x))
        got = np.asarray(pt.block_diag([np.eye(2), np.ones((1, 3))]))
        assert got.shape == (3, 5)
        edges = np.array([0.0, 1.0, 2.0])
        vals = np.array([-0.5, 0.5, 1.5, 2.5])
        np.testing.assert_array_equal(np.asarray(pt.bucketize(vals, edges)),
                                      np.searchsorted(edges, vals))

    def test_crop_unfold_as_strided(self):
        x = A(4, 6)
        got = np.asarray(pt.crop(x, shape=[2, -1], offsets=[1, 2]))
        np.testing.assert_array_equal(got, x[1:3, 2:])
        w = np.asarray(pt.unfold(pt.to_tensor(np.arange(10.0)), 0, 4, 3))
        np.testing.assert_array_equal(w, [[0, 1, 2, 3], [3, 4, 5, 6],
                                          [6, 7, 8, 9]])
        # non-last axis: window dim must land LAST (paddle/torch convention)
        m = A(10, 2)
        w2 = np.asarray(pt.unfold(pt.to_tensor(m), 0, 4, 3))
        assert w2.shape == (3, 2, 4)
        np.testing.assert_allclose(w2[1, 0], m[3:7, 0], rtol=1e-6)
        s = np.asarray(pt.as_strided(pt.to_tensor(np.arange(12.0)),
                                     (3, 2), (4, 1)))
        np.testing.assert_array_equal(
            s, np.lib.stride_tricks.as_strided(
                np.arange(12.0), (3, 2), (32, 8)))


class TestComplexViews:
    def test_complex_roundtrip(self):
        x = A(3, 2)
        c = pt.as_complex(pt.to_tensor(x))
        np.testing.assert_allclose(np.asarray(pt.real(c)), x[:, 0], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.imag(c)), x[:, 1], rtol=1e-6)
        back = np.asarray(pt.as_real(c))
        np.testing.assert_allclose(back, x, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.angle(c)),
                                   np.angle(x[:, 0] + 1j * x[:, 1]), rtol=1e-5)


class TestMiscMath:
    def test_pointwise_oracle(self):
        x = np.abs(A(16)) + 0.1
        y = A(16)
        np.testing.assert_allclose(np.asarray(pt.heaviside(y, x)),
                                   np.heaviside(y, x), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.copysign(x, y)),
                                   np.copysign(x, y), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.frac(y * 3)),
                                   (y * 3) - np.trunc(y * 3), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(pt.deg2rad(x)),
                                   np.deg2rad(x), rtol=1e-6)
        a = np.array([4, 6, 9]); b = np.array([6, 4, 6])
        np.testing.assert_array_equal(np.asarray(pt.gcd(a, b)), np.gcd(a, b))
        np.testing.assert_array_equal(np.asarray(pt.lcm(a, b)), np.lcm(a, b))

    def test_trapezoid_vander(self):
        y = A(9)
        np.testing.assert_allclose(np.asarray(pt.trapezoid(y, dx=0.5)),
                                   np.trapezoid(y, dx=0.5), rtol=1e-5)
        v = A(4)
        np.testing.assert_allclose(np.asarray(pt.vander(v, 3)),
                                   np.vander(v, 3), rtol=1e-5)

    def test_renorm_multiplex_indexput_clipnorm(self):
        x = A(3, 4)
        out = np.asarray(pt.renorm(x, 2.0, 0, 1.0))
        norms = np.linalg.norm(out.reshape(3, -1), axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        cands = [A(4, 2), A(4, 2)]
        idx = np.array([0, 1, 1, 0])
        got = np.asarray(pt.multiplex(
            [pt.to_tensor(c) for c in cands], pt.to_tensor(idx)))
        want = np.stack([cands[idx[i]][i] for i in range(4)])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        z = np.zeros((3, 3), np.float32)
        got = np.asarray(pt.index_put(pt.to_tensor(z),
                                      (np.array([0, 2]), np.array([1, 2])),
                                      np.array([5.0, 7.0], np.float32)))
        assert got[0, 1] == 5 and got[2, 2] == 7
        big = np.ones(8, np.float32) * 10
        clipped = np.asarray(pt.clip_by_norm(pt.to_tensor(big), 1.0))
        np.testing.assert_allclose(np.linalg.norm(clipped), 1.0, rtol=1e-5)

    def test_special_functions(self):
        x = np.abs(A(8)) + 0.5
        import scipy.special as ss
        pytest.importorskip("scipy")
        np.testing.assert_allclose(np.asarray(pt.i0(x)), ss.i0(x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.polygamma(x, 1)),
                                   ss.polygamma(1, x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.gammainc(x, x)),
                                   ss.gammainc(x, x), rtol=1e-4)

    def test_sgn_complex(self):
        c = np.array([3 + 4j, 0 + 0j], np.complex64)
        got = np.asarray(pt.sgn(pt.to_tensor(c)))
        np.testing.assert_allclose(got[0], 0.6 + 0.8j, rtol=1e-5)
        assert got[1] == 0


class TestLinalgExtras:
    def test_triangular_and_cholesky_solve(self):
        a = A(4, 4)
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        b = A(4, 2)
        lo = np.linalg.cholesky(spd).astype("float32")
        got = np.asarray(pt.ops.linalg.triangular_solve(lo.T, b, upper=True))
        want = np.linalg.solve(lo.T, b)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
        got = np.asarray(pt.ops.linalg.cholesky_solve(b, lo, upper=False))
        np.testing.assert_allclose(got, np.linalg.solve(spd, b),
                                   rtol=1e-3, atol=1e-4)

    def test_lu_packed_convention(self):
        import scipy.linalg as sl
        a = A(4, 4) + 4 * np.eye(4, dtype="float32")
        lu, piv = pt.ops.linalg.lu(a)
        want_lu, want_piv = sl.lu_factor(a)
        np.testing.assert_allclose(np.asarray(lu), want_lu, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(piv), want_piv + 1)  # 1-based
        lu2, piv2, infos = pt.ops.linalg.lu(a, get_infos=True)
        assert infos.shape == () and int(infos) == 0

    def test_cov_corrcoef_expm(self):
        x = A(3, 50)
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.cov(x)),
                                   np.cov(x), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.corrcoef(x)),
                                   np.corrcoef(x), rtol=1e-4)
        m = A(3, 3) * 0.1
        import scipy.linalg as sl
        np.testing.assert_allclose(np.asarray(pt.ops.linalg.matrix_exp(m)),
                                   sl.expm(m), rtol=1e-4, atol=1e-5)

    def test_fft_extras(self):
        x = A(8)
        np.testing.assert_allclose(np.asarray(pt.ops.fft.hfft(x)),
                                   np.fft.hfft(x), rtol=1e-4, atol=1e-4)
        c = A(4, 4)
        np.testing.assert_allclose(np.asarray(pt.ops.fft.rfftn(c)),
                                   np.fft.rfftn(c), rtol=1e-4, atol=1e-4)


class TestDistanceAndScatterNd:
    def test_scatter_nd(self):
        index = np.array([[1], [2], [1]], np.int64)
        updates = np.array([9.0, 10.0, 11.0], np.float32)
        out = np.asarray(pt.scatter_nd(index, updates, [4]))
        # duplicates accumulate (paddle.scatter_nd semantics)
        np.testing.assert_allclose(out, [0.0, 20.0, 10.0, 0.0])

    def test_scatter_nd_2d_index(self):
        index = np.array([[0, 1], [2, 3]], np.int64)
        updates = A(2, 5)
        out = np.asarray(pt.scatter_nd(index, updates, [3, 4, 5]))
        expect = np.zeros((3, 4, 5), np.float32)
        expect[0, 1] += updates[0]
        expect[2, 3] += updates[1]
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    @pytest.mark.parametrize("p", [0.0, 1.0, 2.0, 3.0, float("inf")])
    def test_cdist_vs_torch(self, p):
        import torch
        x, y = A(2, 5, 4), A(2, 7, 4)
        ours = np.asarray(pt.cdist(x, y, p=p))
        ref = torch.cdist(torch.tensor(x), torch.tensor(y), p=p).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_cdist_no_mm_matches_mm(self):
        x, y = A(3, 4), A(5, 4)
        mm = np.asarray(pt.cdist(x, y))
        no_mm = np.asarray(
            pt.cdist(x, y, compute_mode="donot_use_mm_for_euclid_dist"))
        np.testing.assert_allclose(mm, no_mm, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("p", [1.0, 2.0])
    def test_pdist_vs_torch(self, p):
        import torch
        x = A(6, 3)
        ours = np.asarray(pt.pdist(x, p=p))
        ref = torch.pdist(torch.tensor(x), p=p).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


class TestRound2TailBatch:
    def test_masked_scatter_vs_torch(self):
        import torch
        x = A(3, 4)
        mask = x > 0
        vals = A(12)
        ours = np.asarray(pt.masked_scatter(x, mask, vals))
        ref = torch.tensor(x).masked_scatter(torch.tensor(mask),
                                             torch.tensor(vals)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-6)

    def test_select_slice_diagonal_scatter_vs_torch(self):
        import torch
        x = A(4, 5)
        v = A(5)
        np.testing.assert_allclose(
            np.asarray(pt.select_scatter(x, v, 0, 2)),
            torch.tensor(x).select_scatter(torch.tensor(v), 0, 2).numpy(),
            rtol=1e-6)
        sl = A(4, 2)
        np.testing.assert_allclose(
            np.asarray(pt.slice_scatter(x, sl, axes=[1], starts=[1],
                                        ends=[5], strides=[2])),
            torch.tensor(x).slice_scatter(torch.tensor(sl), 1, 1, 5,
                                          2).numpy(), rtol=1e-6)
        d = A(4)
        np.testing.assert_allclose(
            np.asarray(pt.diagonal_scatter(x, d)),
            torch.tensor(x).diagonal_scatter(torch.tensor(d)).numpy(),
            rtol=1e-6)

    def test_unique_consecutive(self):
        x = np.array([1, 1, 2, 2, 3, 1, 1, 2], np.int32)
        out, inv, cnt = pt.unique_consecutive(x, return_inverse=True,
                                              return_counts=True)
        np.testing.assert_array_equal(np.asarray(out), [1, 2, 3, 1, 2])
        np.testing.assert_array_equal(np.asarray(inv),
                                      [0, 0, 1, 1, 2, 3, 3, 4])
        np.testing.assert_array_equal(np.asarray(cnt), [2, 2, 1, 2, 1])

    def test_index_sample_and_strided_slice(self):
        x = A(3, 6)
        idx = np.array([[0, 2], [1, 3], [5, 0]])
        np.testing.assert_allclose(
            np.asarray(pt.index_sample(x, idx)),
            np.take_along_axis(x, idx, axis=1), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(pt.strided_slice(x, axes=[1], starts=[1], ends=[6],
                                        strides=[2])),
            x[:, 1:6:2], rtol=1e-6)

    def test_linalg_tail_vs_numpy(self):
        a = A(3, 3)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        chol = np.asarray(pt.cholesky(spd))
        np.testing.assert_allclose(chol @ chol.T, spd, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(pt.cholesky_inverse(chol)), np.linalg.inv(spd),
            rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.matrix_power(a, 3)),
                                   np.linalg.matrix_power(a, 3), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.inverse(spd)),
                                   np.linalg.inv(spd), rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.multi_dot([A(2, 3), A(3, 4), A(4, 2)] )).shape,
            (2, 2))

    def test_blas_tail_vs_torch(self):
        import torch
        x, m, v = A(3), A(3, 4), A(4)
        np.testing.assert_allclose(
            np.asarray(pt.addmv(x, m, v, beta=0.5, alpha=2.0)),
            torch.addmv(torch.tensor(x), torch.tensor(m), torch.tensor(v),
                        beta=0.5, alpha=2.0).numpy(), rtol=1e-5)
        b1, b2, base = A(2, 3, 4), A(2, 4, 5), A(2, 3, 5)
        np.testing.assert_allclose(
            np.asarray(pt.baddbmm(base, b1, b2, beta=0.3, alpha=1.5)),
            torch.baddbmm(torch.tensor(base), torch.tensor(b1),
                          torch.tensor(b2), beta=0.3, alpha=1.5).numpy(),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(pt.mv(m, v)), m @ v,
                                   rtol=1e-5)

    def test_stacks_flips_misc(self):
        a, b = A(3), A(3)
        np.testing.assert_allclose(np.asarray(pt.column_stack([a, b])),
                                   np.column_stack([a, b]))
        np.testing.assert_allclose(np.asarray(pt.hstack([a, b])),
                                   np.hstack([a, b]))
        m = A(2, 3)
        np.testing.assert_allclose(np.asarray(pt.fliplr(m)), np.fliplr(m))
        np.testing.assert_allclose(np.asarray(pt.flipud(m)), np.flipud(m))
        np.testing.assert_allclose(np.asarray(pt.logaddexp(a, b)),
                                   np.logaddexp(a, b), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.fmod(a, 0.3)),
                                   np.fmod(a, 0.3), rtol=1e-5, atol=1e-6)
        assert pt.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        assert int(pt.rank(m)) == 2
        assert pt.is_floating_point(m) and not pt.is_integer(m)
        x = np.array([np.nan, 1.0, 5.0], np.float32)
        assert float(pt.nanmax(x)) == 5.0 and float(pt.nanmin(x)) == 1.0

    def test_index_fill_and_masked_fill_family(self):
        x = A(3, 4)
        out = np.asarray(pt.index_fill(x, np.array([0, 2]), 0, 9.0))
        assert (out[[0, 2]] == 9.0).all() and (out[1] == x[1]).all()

    def test_random_tail_shapes(self):
        import paddle_tpu as p
        assert p.standard_normal([3, 4]).shape == (3, 4)
        g = p.standard_gamma(np.full((5,), 2.0, np.float32))
        assert g.shape == (5,) and (np.asarray(g) > 0).all()
        lam = np.full((4,), 3.0, np.float32)
        assert p.poisson(lam).shape == (4,)
        b = p.binomial(np.full((6,), 10, np.int32),
                       np.full((6,), 0.5, np.float32))
        assert (np.asarray(b) <= 10).all() and (np.asarray(b) >= 0).all()

    def test_assign_clone_detach(self):
        import jax
        x = jnp_ones = pt.ones([2, 2])
        y = pt.assign(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))
        g = jax.grad(lambda v: (pt.detach(v) * v).sum())(
            pt.ones([3]))
        # detach blocks the first factor's gradient: d/dv (c*v) = c = 1
        np.testing.assert_allclose(np.asarray(g), 1.0)


class TestRound2ReviewRegressions:
    def test_diagonal_scatter_nonsquare_offsets(self):
        import torch
        x = A(4, 5)
        for off in (-2, -1, 0, 1, 2):
            n = torch.tensor(x).diagonal(offset=off).shape[0]
            d = A(n)
            np.testing.assert_allclose(
                np.asarray(pt.diagonal_scatter(x, d, offset=off)),
                torch.tensor(x).diagonal_scatter(torch.tensor(d),
                                                 offset=off).numpy(),
                rtol=1e-6)

    def test_masked_scatter_too_few_values_raises(self):
        x = A(3, 4)
        mask = np.ones((3, 4), bool)
        with pytest.raises(ValueError, match="fewer|selects"):
            pt.masked_scatter(x, mask, A(5))

    def test_sparse_softmax_3d(self):
        from paddle_tpu import sparse as S
        t = S.sparse_coo_tensor([[0, 0, 1], [0, 1, 1], [0, 0, 2]],
                                [1.0, 2.0, 3.0], (2, 2, 3))
        d = np.asarray(S.softmax(t).to_dense())
        # each (i,j) row with nonzeros normalizes independently
        np.testing.assert_allclose(d[0, 0, 0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(d[0, 1, 0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(d[1, 1, 2], 1.0, rtol=1e-5)

    def test_cholesky_inverse_accuracy(self):
        a = A(4, 4)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        l = np.linalg.cholesky(spd)
        np.testing.assert_allclose(np.asarray(pt.cholesky_inverse(l)),
                                   np.linalg.inv(spd), rtol=1e-4, atol=1e-5)
