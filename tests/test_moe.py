"""MoE / expert-parallel tests (reference pattern: moe tests under
test/collective/fleet — route, train, compare ep-sharded vs single-device).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.moe import (GShardGate, MoELayer, SwitchGate,
                                        limit_by_capacity, number_count)
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import mixtral as mixtral_mod
from paddle_tpu.models.mixtral import mixtral
from paddle_tpu.nn.layer import functional_call, raw_params


@pytest.fixture(autouse=True)
def _fleet_reset():
    yield
    fleet._reset()


def test_number_count_and_capacity():
    idx = jnp.asarray([0, 1, 0, 2, 0, 1], jnp.int32)
    counts = number_count(idx, 4)
    np.testing.assert_array_equal(np.asarray(counts), [3, 2, 1, 0])
    mask = jax.nn.one_hot(idx, 4, dtype=jnp.float32)
    kept, pos = limit_by_capacity(mask, capacity=2)
    # expert 0 got 3 tokens; the third (token idx 4) must be dropped
    np.testing.assert_array_equal(np.asarray(kept[:, 0]), [1, 0, 1, 0, 0, 0])


@pytest.mark.parametrize("gate_cls", [SwitchGate, GShardGate])
def test_gate_routing_properties(gate_cls):
    pt.seed(0)
    gate = gate_cls(16, 4, capacity_factor=2.0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)),
                    jnp.float32)
    combine, dispatch, aux = gate(x)
    C = gate.capacity(32)
    assert combine.shape == (32, 4, C)
    assert float(aux) > 0
    # each token's combine weights sum to <= 1 (== 1 unless dropped)
    sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert (sums <= 1.0 + 1e-5).all()
    # capacity respected: each (expert, slot) holds at most one token
    slot_use = np.asarray(jnp.sum((combine > 0).astype(jnp.int32), axis=0))
    assert (slot_use <= 1).all()


def test_moe_layer_forward_and_identity_experts():
    """With experts initialised to identity-like behaviour the layer output
    equals combine·dispatch reconstruction of the input (routing algebra)."""

    class Identity(nn.Layer):
        def __init__(self):
            super().__init__()
            self.scale = self.create_parameter(
                (1,), default_initializer=lambda k, s, d: jnp.ones(s, d))

        def forward(self, h):
            return h * self.scale

    pt.seed(0)
    layer = MoELayer(8, Identity, num_experts=4, gate="switch",
                     capacity_factor=4.0)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8)),
                    jnp.float32)
    out = layer(x)
    assert out.shape == x.shape
    # identity experts: out_token = (sum of its combine weights) * token
    tokens = x.reshape(-1, 8)
    combine, dispatch, _ = layer.gate(tokens)
    g = jnp.sum(combine, axis=(1, 2))                   # [N]
    np.testing.assert_allclose(np.asarray(out.reshape(-1, 8)),
                               np.asarray(g[:, None] * tokens),
                               rtol=1e-4, atol=1e-5)


def test_mixtral_ep_matches_single_device():
    ids = np.random.default_rng(0).integers(0, 256, size=(4, 16))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32),
             "labels": jnp.asarray(np.roll(ids, -1, 1), jnp.int32)}

    def run(hybrid, steps=3):
        fleet._reset()
        pt.seed(0)
        mesh = None
        if hybrid:
            s = fleet.DistributedStrategy()
            s.hybrid_configs = hybrid
            mesh = fleet.init(strategy=s).mesh
        model = mixtral("tiny")
        # deterministic routing for the equivalence check
        for _, sub in model.named_sublayers():
            if isinstance(sub, GShardGate):
                sub.random_routing = False
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, mixtral_mod.causal_lm_loss, opt, mesh=mesh)
        state = step.init_state(seed=0)
        losses = []
        for _ in range(steps):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    serial = run(None)
    ep = run({"ep_degree": 4, "dp_degree": 2})
    np.testing.assert_allclose(serial, ep, rtol=2e-4)
    ep_mp = run({"ep_degree": 2, "mp_degree": 2})
    np.testing.assert_allclose(serial, ep_mp, rtol=2e-4)


def test_expert_params_sharded_over_ep():
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"ep_degree": 4}
    fleet.init(strategy=s)
    pt.seed(0)
    model = mixtral("tiny")
    meta = model.param_meta()
    expert_params = [k for k in meta if "block_sparse_moe" in k
                     and "gate" not in k]
    assert expert_params
    for k in expert_params:
        assert meta[k].partition[0] == "ep", (k, meta[k].partition)


def test_aux_loss_reaches_objective():
    pt.seed(0)
    model = mixtral("tiny", num_hidden_layers=1)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)),
                      jnp.int32)
    params = raw_params(model)

    def loss_with(coef):
        import dataclasses
        model.cfg = dataclasses.replace(model.cfg,
                                        router_aux_loss_coef=coef)
        return float(functional_call(model, params, ids,
                                     labels=jnp.roll(ids, -1, 1)))

    assert loss_with(10.0) > loss_with(0.0)


def test_mixtral_under_recompute_and_pipeline():
    """Aux losses flow through function outputs, so MoE composes with
    jax.checkpoint (use_recompute) and the pipelined scan/vmap schedule —
    the configurations a side-channel accumulator would crash with
    escaped-tracer errors."""
    ids = np.random.default_rng(0).integers(0, 256, size=(4, 16))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32),
             "labels": jnp.asarray(np.roll(ids, -1, 1), jnp.int32)}

    def run(**model_kwargs):
        fleet._reset()
        pt.seed(0)
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"ep_degree": 2, "pp_degree": 2}
        mesh = fleet.init(strategy=s).mesh
        model = mixtral("tiny", **model_kwargs)
        for _, sub in model.named_sublayers():
            if isinstance(sub, GShardGate):
                sub.random_routing = False
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, mixtral_mod.causal_lm_loss, opt, mesh=mesh)
        state = step.init_state(seed=0)
        state, m = step(state, batch)
        return float(m["loss"])

    l_remat = run(use_recompute=True)
    assert np.isfinite(l_remat)
    l_pp = run(pipeline_stages=2, num_microbatches=2)
    assert np.isfinite(l_pp)
    l_pp_remat = run(pipeline_stages=2, num_microbatches=2,
                     use_recompute=True)
    assert np.isfinite(l_pp_remat)


def test_moe_layer_respects_eval_mode():
    """train()/eval() must reach the hidden expert template."""

    class DropExpert(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, h):
            return nn.functional.dropout(self.fc(h), p=0.5,
                                         training=self.training)

    pt.seed(0)
    layer = MoELayer(8, DropExpert, num_experts=2, gate="switch",
                     capacity_factor=4.0)
    layer.eval()
    assert not layer.template.training
    x = jnp.ones((4, 8), jnp.float32)
    a = layer(x)
    b = layer(x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))  # no dropout

    layer.train()
    assert layer.template.training


def test_top_k_validation():
    with pytest.raises(ValueError):
        MoELayer(8, lambda: nn.Linear(8, 8), num_experts=2, gate="gshard",
                 top_k=1)


class TestEvalDroplessRouting:
    def test_eval_capacity_is_dropless_by_default(self):
        from paddle_tpu.distributed.moe import NaiveGate
        import paddle_tpu as pt
        pt.seed(0)
        g = NaiveGate(8, num_experts=4, capacity_factor=1.25)
        g.eval()
        assert g.capacity(100) == 100   # dropless: every token fits anywhere
        g.train()
        assert g.capacity(100) == max(int(1.25 * 100 * 2 / 4), 4)  # capped

    def test_eval_factor_override_still_caps(self):
        from paddle_tpu.distributed.moe import NaiveGate
        g = NaiveGate(8, num_experts=4, capacity_factor=1.25,
                      eval_capacity_factor=1.0)
        g.eval()
        assert g.capacity(100) == int(1.0 * 100 * 2 / 4)
