"""Serial-vs-parallel equivalence tests (the reference's key correctness
pattern: test/collective/fleet/hybrid_parallel_mp_layers.py — parallel
numerics must equal the single-process run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mp_layers import (ColumnParallelLinear,
                                              ParallelCrossEntropy,
                                              RowParallelLinear,
                                              VocabParallelEmbedding)
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.llama import PRESETS, causal_lm_loss, llama
from paddle_tpu.nn.layer import raw_params


@pytest.fixture(autouse=True)
def reset_fleet():
    yield
    fleet._reset()


def _init_mp(mp=2, dp=1, sharding=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "sharding_degree": sharding}
    return fleet.init(is_collective=True, strategy=strategy)


class MpBlock(nn.Layer):
    """Column->Row pair, the canonical Megatron block."""

    def __init__(self):
        super().__init__()
        self.col = ColumnParallelLinear(16, 32, has_bias=True)
        self.row = RowParallelLinear(32, 16, has_bias=True)

    def forward(self, x):
        return self.row(nn.functional.relu(self.col(x)))


def test_topology_mesh_shape():
    hcg = _init_mp(mp=2, dp=2, sharding=2)
    assert hcg.mesh.shape["mp"] == 2
    assert hcg.mesh.shape["dp"] == 2
    assert hcg.mesh.shape["sharding"] == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.topology.world_size == 8
    assert sorted(hcg.active_axes()) == ["dp", "mp", "sharding"]


def test_mp_forward_matches_serial():
    # build serial weights first (no mesh)
    pt.seed(0)
    serial = MpBlock()
    sd = {k: np.asarray(v) for k, v in serial.state_dict().items()}
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 16)).astype(np.float32))
    # Note: 2-D activations — constrain specs in mp_layers expect 3-D [b,s,h]
    x3 = x[:, None, :]
    y_serial = serial(x3)

    hcg = _init_mp(mp=2)
    parallel = MpBlock()
    parallel.set_state_dict(sd)
    step_fn = jax.jit(lambda p, xx: pt.nn.functional_call(parallel, p, xx))
    with hcg.mesh:
        params = {k: jax.device_put(v) for k, v in raw_params(parallel).items()}
        y_par = step_fn(params, x3)
    np.testing.assert_allclose(np.asarray(y_par), np.asarray(y_serial),
                               rtol=2e-5, atol=2e-6)


def test_vocab_parallel_embedding_and_ce():
    pt.seed(0)
    emb_serial = VocabParallelEmbedding(64, 16)
    w = np.asarray(emb_serial.weight)
    ids = jnp.asarray([[1, 5, 63, 0]])
    out_serial = emb_serial(ids)

    hcg = _init_mp(mp=2)
    emb_par = VocabParallelEmbedding(64, 16)
    emb_par.set_state_dict({"weight": w})
    with hcg.mesh:
        out_par = jax.jit(lambda p, i: pt.nn.functional_call(emb_par, p, i))(
            raw_params(emb_par), ids)
    np.testing.assert_allclose(np.asarray(out_par), np.asarray(out_serial),
                               rtol=1e-6)

    # vocab-parallel CE == serial CE
    logits = jnp.asarray(np.random.default_rng(1).standard_normal(
        (2, 4, 64)).astype(np.float32))
    labels = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]])
    ce = ParallelCrossEntropy()
    serial_loss = nn.functional.cross_entropy(logits, labels, reduction="none")
    with hcg.mesh:
        par_loss = jax.jit(lambda l, y: ce(l, y))(logits, labels)
    np.testing.assert_allclose(np.asarray(par_loss), np.asarray(serial_loss),
                               rtol=1e-5, atol=1e-6)


def test_llama_tiny_forward_and_learn():
    pt.seed(0)
    model = llama("tiny")
    batch = {
        "input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32))),
        "labels": jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32))),
    }
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, causal_lm_loss, opt)
    state = step.init_state(0)
    losses = []
    for _ in range(30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_llama_tp_matches_serial():
    """One full train step under mp=2+dp=2 == serial step (same init)."""
    pt.seed(0)
    serial_model = llama("tiny")
    sd = {k: np.asarray(v) for k, v in serial_model.state_dict().items()}
    batch = {
        "input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 16))),
        "labels": jnp.asarray(np.random.default_rng(1).integers(0, 256, (4, 16))),
    }
    opt_s = optimizer.AdamW(learning_rate=1e-2, parameters=serial_model.parameters())
    step_s = TrainStep(serial_model, causal_lm_loss, opt_s)
    state_s = step_s.init_state(0)
    state_s, m_s = step_s(state_s, batch)
    state_s, m_s2 = step_s(state_s, batch)

    hcg = _init_mp(mp=2, dp=2)
    par_model = llama("tiny")
    par_model.set_state_dict(sd)
    opt_p = optimizer.AdamW(learning_rate=1e-2, parameters=par_model.parameters())
    opt_p = fleet.distributed_optimizer(opt_p)
    step_p = TrainStep(par_model, causal_lm_loss, opt_p, mesh=hcg.mesh)
    state_p = step_p.init_state(0)
    state_p, m_p = step_p(state_p, batch)
    state_p, m_p2 = step_p(state_p, batch)

    np.testing.assert_allclose(float(m_p["loss"]), float(m_s["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_p2["loss"]), float(m_s2["loss"]),
                               rtol=1e-4)
    # spot-check a sharded weight tracks the serial trajectory (Adam's
    # rsqrt(v) amplifies fp32 reduction-order noise in early steps, so the
    # bound is looser than the loss parity above)
    k = "model.layers.0.self_attn.q_proj.weight"
    np.testing.assert_allclose(np.asarray(state_p["params"][k]),
                               np.asarray(state_s["params"][k]),
                               rtol=5e-3, atol=3e-4)


def test_llama_sequence_parallel_matches():
    pt.seed(0)
    serial_model = llama("tiny")
    sd = {k: np.asarray(v) for k, v in serial_model.state_dict().items()}
    batch = {
        "input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32))),
        "labels": jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 32))),
    }
    out_serial = pt.nn.functional_call(serial_model, raw_params(serial_model),
                                       batch["input_ids"],
                                       labels=batch["labels"])

    hcg = _init_mp(mp=2)
    sp_model = llama("tiny", sequence_parallel=True)
    sp_model.set_state_dict(sd)
    with hcg.mesh:
        out_sp = jax.jit(lambda p, b: pt.nn.functional_call(
            sp_model, p, b["input_ids"], labels=b["labels"]))(
                raw_params(sp_model), batch)
    np.testing.assert_allclose(float(out_sp), float(out_serial), rtol=2e-5)


def test_zero_sharding_specs():
    """ZeRO-1: optimizer state sharded over data axes; ZeRO-3: params too."""
    hcg = _init_mp(mp=1, dp=2, sharding=2)
    model = llama("tiny")
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, causal_lm_loss, opt, mesh=hcg.mesh, zero_stage=1)
    state = step.init_state(0)
    # a moment slot should be sharded over dp/sharding on dim 0
    m1 = state["opt"]["moment1"]["model.layers.0.mlp.gate_proj.weight"]
    assert "dp" in str(m1.sharding.spec) or "sharding" in str(m1.sharding.spec)
    # params not sharded over the data axes at stage 1 (mp annotation stays)
    p = state["params"]["model.layers.0.mlp.gate_proj.weight"]
    spec_str = str(p.sharding.spec)
    assert "dp" not in spec_str and "sharding" not in spec_str

    step3 = TrainStep(model, causal_lm_loss, opt, mesh=hcg.mesh, zero_stage=3)
    state3 = step3.init_state(0)
    p3 = state3["params"]["model.layers.0.mlp.gate_proj.weight"]
    assert any(e is not None for e in p3.sharding.spec)
    # and it still trains
    batch = {
        "input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 16))),
        "labels": jnp.asarray(np.random.default_rng(1).integers(0, 256, (4, 16))),
    }
    state3, m = step3(state3, batch)
    assert np.isfinite(float(m["loss"]))


def test_llama_initializer_range_applied():
    pt.seed(0)
    small = llama("tiny", initializer_range=0.001)
    pt.seed(0)
    big = llama("tiny", initializer_range=0.5)
    ws = np.asarray(small.model.layers[0].self_attn.q_proj.weight)
    wb = np.asarray(big.model.layers[0].self_attn.q_proj.weight)
    assert ws.std() < 0.01 and wb.std() > 0.1
