"""Disaggregated serving (paddle_tpu.serving.disagg): prefill/decode
role specialization with KV-page streaming.

The load-bearing guarantees (docs/SERVING.md "Disaggregated serving"):

- a ``role="prefill"`` engine retires each request at prefill-complete
  (first token emitted, pages swapped out, slot freed) and a
  ``role="decode"`` engine resumes it from a transferred ``KVHandout``
  through the restore path — greedy outputs TOKEN-IDENTICAL to a
  colocated engine, zero recompiles;
- the ``KVTransport`` wire format round-trips pages (int8 scales and
  mid-prefill kv_len included) through bytes with chunked crc-verified
  retried I/O; a hard transfer failure degrades to a fresh re-prefill;
- the ``DisaggReplicaSet`` duck-types the Engine surface behind the
  unchanged FrontDoor, keeps trace ids + exact phase accounting across
  the handoff (the ``xfer`` segment), and survives replica death in
  either role.
"""

import http.client
import json
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import resilience as rs
from paddle_tpu import serving
from paddle_tpu.launch.store import TCPStore
from paddle_tpu.serving import (DisaggReplicaSet, HeartbeatMonitor,
                                KVHandout, LoopbackTransport,
                                StoreTransport, SwapManager,
                                TransferError)

R = np.random.default_rng(0)
PROMPTS = [R.integers(0, 256, size=n).astype(np.int32)
           for n in (5, 17, 9, 26)]
SHARED = R.integers(0, 256, size=16).astype(np.int32)   # 2 full pages


def _prompt(n):
    return R.integers(0, 256, size=n).astype(np.int32)


@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(model, **kw)


def _serve(tgt, prompts, max_new=6, **kw):
    rids = [tgt.add_request(p, max_new_tokens=max_new, **kw)
            for p in prompts]
    outs = tgt.run()
    return [outs[r] for r in rids]


@pytest.fixture(scope="module")
def reference(tiny_llama):
    """Colocated greedy outputs for the shared prompt mix."""
    eng = _engine(tiny_llama).warmup()
    return _serve(eng, PROMPTS)


def _disagg(model, n_prefill=1, n_decode=2, transport=None, **kw):
    pre = [_engine(model, role="prefill", **kw).warmup()
           for _ in range(n_prefill)]
    dec = [_engine(model, role="decode", **kw).warmup()
           for _ in range(n_decode)]
    return DisaggReplicaSet(pre, dec, transport=transport), pre, dec


# ---------------------------------------------------------------------------
# SwapManager wire format (the contract KVTransport relies on)
# ---------------------------------------------------------------------------

class TestSwapPayloadBytes:
    def _payload_roundtrip(self, model, dtype):
        eng = _engine(model, kv_cache_dtype=dtype).warmup()
        rid = eng.add_request(_prompt(19), max_new_tokens=4)
        eng.step()                       # mid-prefill: 8 of 19 tokens
        # pdtpu-lint: disable=lock-discipline — single-threaded test
        st = eng._states[rid]
        assert st.prefilling and 0 < st.kv_len < 19
        assert eng.preempt(rid)
        pages, host = st.swapped
        blob = SwapManager.payload_to_bytes(host)
        back = SwapManager.payload_from_bytes(blob)
        assert len(back) == len(host)
        for hl, bl in zip(host, back):
            assert len(hl) == len(bl)
            for h, b in zip(hl, bl):
                assert h.dtype == b.dtype and h.shape == b.shape
                assert h.tobytes() == b.tobytes()
        eng.run()
        assert eng.kv_blocks_used == 0
        return host

    def test_fp32_roundtrip_mid_prefill(self, tiny_llama):
        host = self._payload_roundtrip(tiny_llama, None)
        assert len(host[0]) == 2         # (k, v) per layer

    def test_int8_scales_ride_the_blob(self, tiny_llama):
        host = self._payload_roundtrip(tiny_llama, "int8")
        # int8 pools: (k_i8, v_i8, k_scale, v_scale) per layer — the
        # scale rows MUST survive the wire or restored KV dequantizes
        # wrong
        assert len(host[0]) == 4
        assert str(host[0][0].dtype) == "int8"
        assert str(host[0][2].dtype) == "float32"

    def test_bfloat16_dtype_survives(self):
        # regression: np.dtype(bf16).str collapses to "<V2" and does
        # not round-trip; the wire format must serialize by NAME
        import jax.numpy as jnp
        a = np.asarray(jnp.arange(8, dtype=jnp.bfloat16)).reshape(2, 4)
        host = [(a, a + 1)]
        back = SwapManager.payload_from_bytes(
            SwapManager.payload_to_bytes(host))
        assert str(back[0][0].dtype) == "bfloat16"
        assert back[0][1].tobytes() == (a + 1).tobytes()

    def test_framing_mismatch_raises(self):
        host = [(np.zeros((1, 2), np.float32),)]
        blob = SwapManager.payload_to_bytes(host)
        with pytest.raises(ValueError, match="framing"):
            SwapManager.payload_from_bytes(blob + b"\x00")


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class TestKVTransport:
    def test_loopback_roundtrip_chunked(self):
        tp = LoopbackTransport(chunk_bytes=16)
        data = bytes(range(256)) * 3
        n = tp.put("k1", data)
        assert n == -(-len(data) // 16)
        assert tp.get("k1") == data
        # delete-on-get reclaimed the store
        assert len(tp) == 0
        with pytest.raises(TransferError, match="meta"):
            tp.get("k1")

    def test_get_without_delete_rereads(self):
        tp = LoopbackTransport()
        tp.put("k", b"payload")
        assert tp.get("k", delete=False) == b"payload"
        assert tp.get("k") == b"payload"

    def test_crc_corruption_detected(self):
        tp = LoopbackTransport(chunk_bytes=16)
        tp.put("k", b"x" * 40)
        # flip a byte inside chunk 1's payload
        framed = bytearray(tp._blobs[("k", "c", 1)])
        framed[10] ^= 0xFF
        tp._blobs[("k", "c", 1)] = bytes(framed)
        with pytest.raises(TransferError, match="crc32"):
            tp.get("k")
        assert tp.crc_errors >= 1

    def test_transient_fault_retried(self):
        tp = LoopbackTransport()
        rs.install_faults("serve.xfer.put@0:ConnectionError,"
                          "serve.xfer.get@0:ConnectionError")
        try:
            tp.put("k", b"abc")          # first attempt faults, retry lands
            assert tp.get("k") == b"abc"
        finally:
            rs.clear_faults()

    def test_fault_exhaustion_is_hard(self):
        tp = LoopbackTransport()
        rs.install_faults("serve.xfer.put@0x9")
        try:
            with pytest.raises(rs.InjectedFault):
                tp.put("k", b"abc")
        finally:
            rs.clear_faults()

    def test_store_transport_over_tcpstore(self):
        store = TCPStore("127.0.0.1:0", is_master=True)
        try:
            tp = StoreTransport(store, chunk_bytes=32, op_timeout_s=15.0)
            data = bytes(range(200)) * 2
            tp.put("req-1/0", data)
            # chunks + meta actually live on the store under the prefix
            assert store.get("serve/xfer/req-1/0/meta") is not None
            assert tp.get("req-1/0") == data
            assert store.get("serve/xfer/req-1/0/meta") is None
        finally:
            store.close()


# ---------------------------------------------------------------------------
# the wire unit
# ---------------------------------------------------------------------------

class TestKVHandout:
    def _handed_off_state(self, model, **kw):
        eng = _engine(model, role="prefill", **kw).warmup()
        rid = eng.add_request(_prompt(9), max_new_tokens=5,
                              temperature=0.7, tenant="acme")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            while not eng.handed_off:
                eng.step()
        st = eng.handed_off.popleft()
        assert st.request.request_id == rid
        return eng, st

    def test_roundtrip_preserves_resume_state(self, tiny_llama):
        _eng, st = self._handed_off_state(tiny_llama)
        st.request.trace_id = "tr-test-1"
        h = KVHandout.from_state(st)
        h2 = KVHandout.from_bytes(h.to_bytes())
        cb_hits = []
        st2 = h2.to_state(on_token=lambda *a: cb_hits.append(a))
        assert st2.request.request_id == st.request.request_id
        assert st2.request.trace_id == "tr-test-1"
        assert st2.request.tenant == "acme"
        assert st2.request.temperature == pytest.approx(0.7)
        assert np.array_equal(st2.request.prompt_ids,
                              st.request.prompt_ids)
        assert st2.kv_len == st.kv_len == 9
        assert st2.pending_token == st.pending_token
        assert st2.output_ids == st.output_ids and len(st2.output_ids) == 1
        assert st2.sample_seed == st.sample_seed
        assert st2.first_token_t == st.first_token_t
        assert st2.swapped[0] == st.swapped[0]
        assert st2.request.on_token is not None
        for hl, bl in zip(st.swapped[1], st2.swapped[1]):
            for a, b in zip(hl, bl):
                assert a.tobytes() == b.tobytes()

    def test_from_state_requires_swapped(self, tiny_llama):
        eng = _engine(tiny_llama).warmup()
        rid = eng.add_request(_prompt(5), max_new_tokens=2)
        # pdtpu-lint: disable=lock-discipline — single-threaded test
        with pytest.raises(ValueError, match="swapped"):
            KVHandout.from_state(eng._states[rid])
        eng.run()


# ---------------------------------------------------------------------------
# role-specialized engines
# ---------------------------------------------------------------------------

class TestPrefillRole:
    def test_retires_at_prefill_complete(self, tiny_llama):
        eng = _engine(tiny_llama, role="prefill").warmup()
        rid = eng.add_request(_prompt(9), max_new_tokens=5)
        events = []
        while eng.has_work():
            events.extend(eng.step())
        # exactly the first token was emitted here (TTFT is prefill-side)
        assert [e.request_id for e in events] == [rid]
        assert not events[0].finished
        st = eng.handed_off[0]
        assert st.slot is None and not st.blocks   # slot freed, pages out
        assert st.swapped is not None and st.swapped[0] == 2
        assert eng.kv_blocks_used == 0             # only cached pages left
        assert eng.handoffs == 1
        # pdtpu-lint: disable=lock-discipline — single-threaded test
        assert eng._states[rid] is st              # set pops it from here

    def test_finishing_request_never_hands_off(self, tiny_llama):
        eng = _engine(tiny_llama, role="prefill").warmup()
        rid = eng.add_request(_prompt(7), max_new_tokens=1)
        outs = eng.run()
        assert len(outs[rid]) == 1 and not eng.handed_off
        assert eng.handoffs == 0

    def test_veto_hook_decodes_locally(self, tiny_llama, reference):
        eng = _engine(tiny_llama, role="prefill").warmup()
        eng._handoff_ok = lambda: False
        got = _serve(eng, PROMPTS)
        assert got == reference and not eng.handed_off

    def test_bad_role_rejected(self, tiny_llama):
        with pytest.raises(ValueError, match="role"):
            _engine(tiny_llama, role="verifier")

    def test_decode_engine_geometry_mismatch_rejected(self, tiny_llama):
        _eng, st = TestKVHandout()._handed_off_state(tiny_llama)
        other = _engine(tiny_llama, role="decode",
                        kv_cache_dtype="int8").warmup()
        with pytest.raises(ValueError, match="geometry"):
            other.admit_handout(KVHandout.from_state(st))


# ---------------------------------------------------------------------------
# the disaggregated set
# ---------------------------------------------------------------------------

class TestDisaggSet:
    def test_token_identity_vs_colocated(self, tiny_llama, reference):
        ds, _pre, _dec = _disagg(tiny_llama)
        got = _serve(ds, PROMPTS)
        assert got == reference
        st = ds.disagg_stats()
        assert st["handoffs"] == len(PROMPTS) and st["xfers"] > 0
        assert st["xfer_bytes"] > 0
        for r in ds.replicas:
            assert r.kv_blocks_used == 0

    def test_token_identity_int8_pools(self, tiny_llama):
        ref = _serve(_engine(tiny_llama, kv_cache_dtype="int8").warmup(),
                     PROMPTS)
        ds, _p, _d = _disagg(tiny_llama, kv_cache_dtype="int8")
        assert _serve(ds, PROMPTS) == ref

    def test_temperature_stream_reproducible(self, tiny_llama):
        # one prefill replica → same per-engine submission ordinals as
        # the colocated engine → identical sampling streams
        ref = _serve(_engine(tiny_llama).warmup(), PROMPTS,
                     temperature=0.8)
        ds, _p, _d = _disagg(tiny_llama, n_decode=2)
        assert _serve(ds, PROMPTS, temperature=0.8) == ref

    def test_prefix_hits_on_the_prefill_tier(self, tiny_llama):
        ds, pre, _d = _disagg(tiny_llama)
        _serve(ds, [SHARED], max_new=4)
        _serve(ds, [SHARED], max_new=4)
        assert sum(e.prefix_stats()["hits"] for e in pre) > 0

    def test_requires_both_tiers_and_roles(self, tiny_llama):
        e = _engine(tiny_llama, role="prefill")
        with pytest.raises(ValueError, match="at least one"):
            DisaggReplicaSet([e], [])
        with pytest.raises(ValueError, match="role"):
            DisaggReplicaSet([e], [_engine(tiny_llama, role="both")])

    def test_decode_replica_kill_reenters_handoff_queue(
            self, tiny_llama, reference):
        ds, _pre, _dec = _disagg(tiny_llama, n_decode=2)
        rids = [ds.add_request(p, max_new_tokens=6) for p in PROMPTS]
        for _ in range(4):
            ds.step()
        victim = ds._decode_idx[0]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ds._fail_replica(victim, RuntimeError("killed"))
            outs = ds.run()
        assert [outs[r] for r in rids] == reference
        assert not ds._health[victim] and ds.failures == 1
        for r in ds.replicas:
            assert r.kv_blocks_used == 0

    def test_prefill_replica_kill_reroutes_admissions(self, tiny_llama,
                                                      reference):
        ds, _pre, _dec = _disagg(tiny_llama, n_prefill=2, n_decode=1)
        rids = [ds.add_request(p, max_new_tokens=6) for p in PROMPTS]
        ds.step()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ds._fail_replica(0, RuntimeError("prefill host lost"))
            outs = ds.run()
        assert [outs[r] for r in rids] == reference
        for r in ds.replicas:
            assert r.kv_blocks_used == 0

    def test_hard_xfer_failure_falls_back_to_reprefill(
            self, tiny_llama, reference):
        ds, _pre, _dec = _disagg(tiny_llama)
        rs.install_faults("serve.xfer.put@0x50")   # every put dies hard
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                got = _serve(ds, PROMPTS)
        finally:
            rs.clear_faults()
        assert got == reference                    # greedy regenerates
        assert ds.xfer_failures == len(PROMPTS) and ds.xfers == 0
        for r in ds.replicas:
            assert r.kv_blocks_used == 0

    def test_no_decode_tier_degrades_to_colocated(self, tiny_llama,
                                                  reference):
        ds, _pre, _dec = _disagg(tiny_llama, n_decode=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ds._fail_replica(ds._decode_idx[0], RuntimeError("gone"))
            got = _serve(ds, PROMPTS)
        # the prefill replica kept every request and decoded locally
        assert got == reference
        assert ds.disagg_stats()["handoffs"] == 0

    def test_duplicate_request_id_rejected(self, tiny_llama):
        ds, _p, _d = _disagg(tiny_llama, n_decode=1)
        ds.add_request(_prompt(5), max_new_tokens=2, request_id="dup")
        with pytest.raises(serving.AdmissionError, match="dup"):
            ds.add_request(_prompt(5), max_new_tokens=2,
                           request_id="dup")
        ds.run()

    def test_frontdoor_drives_the_set_unchanged(self, tiny_llama,
                                                reference):
        ds, _p, _d = _disagg(tiny_llama)
        door = serving.FrontDoor(ds, policies={
            "hi": serving.TenantPolicy(priority=1)})
        adms = [door.submit(p, tenant="hi" if i % 2 else "default",
                            max_new_tokens=6)
                for i, p in enumerate(PROMPTS)]
        assert all(a.admitted for a in adms)
        outs = door.run()
        assert [outs[a.request_id] for a in adms] == reference

    def test_heartbeat_reap_evacuates(self, tiny_llama, reference):
        store = TCPStore("127.0.0.1:0", is_master=True)
        try:
            ds, _p, _d = _disagg(tiny_llama, n_decode=2)
            # interval_s=0: beat+reap every step (production defaults
            # to deadline/3 so liveness is not per-token store I/O)
            ds.attach_heartbeats(HeartbeatMonitor(store, 3,
                                                  deadline_s=30.0,
                                                  interval_s=0.0))
            rids = [ds.add_request(p, max_new_tokens=6) for p in PROMPTS]
            ds.step()
            ds.step()
            victim = ds._decode_idx[0]
            store.set(f"serve/hb/{victim}", b"not-a-heartbeat")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                outs = ds.run()
            assert not ds._health[victim]
            assert [outs[r] for r in rids] == reference
        finally:
            store.close()

    def test_heartbeat_driver_stall_does_not_self_destruct(
            self, tiny_llama, reference):
        """A step-loop pause longer than the deadline makes every beat
        look stale at once — the reap must recognize its OWN stall and
        re-beat instead of destroying the whole healthy set."""
        store = TCPStore("127.0.0.1:0", is_master=True)
        try:
            clk = [100.0]
            ds, _p, _d = _disagg(tiny_llama, n_decode=1)
            ds.attach_heartbeats(HeartbeatMonitor(
                store, 2, deadline_s=5.0, interval_s=0.0,
                clock=lambda: clk[0]))
            rids = [ds.add_request(p, max_new_tokens=6) for p in PROMPTS]
            ds.step()                 # beats land at t=100
            clk[0] += 60.0            # the driver stalls 60s > deadline
            outs = ds.run()
            assert all(ds._health), "a driver stall reaped live replicas"
            assert [outs[r] for r in rids] == reference
        finally:
            store.close()

    def test_hard_transfer_failure_reclaims_store_entries(
            self, tiny_llama):
        """A half-put transfer must not pin its chunks in the store
        forever — the hard-failure path discards them."""
        ds, _p, _d = _disagg(tiny_llama, n_decode=1)
        tp = ds.transport
        rs.install_faults("serve.xfer.get@0x99")   # every get dies hard
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                _serve(ds, PROMPTS[:2])
        finally:
            rs.clear_faults()
        assert ds.xfer_failures == 2
        assert len(tp) == 0, "abandoned transfers left store entries"

    def test_trace_xfer_segment_and_exact_sum(self, tiny_llama):
        from paddle_tpu import observability as obs
        obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
        try:
            ds, _p, _d = _disagg(tiny_llama)
            rids = [ds.add_request(p, max_new_tokens=6) for p in PROMPTS]
            ds.run()
            tracer = obs.get_request_tracer()
            for r in rids:
                tl = tracer.timeline(r)
                assert tl["summary"]["done"]
                assert tl["summary"]["handoffs"] == 1
                xfer = [e for e in tl["events"]
                        if e.get("closed") == "xfer"]
                assert len(xfer) == 1 and xfer[0]["ms"] >= 0
                assert xfer[0]["phase"] == "xfer"
                s = tl["summary"]
                assert abs(s["queue_ms"] + s["prefill_ms"] + s["xfer_ms"]
                           + s["decode_ms"] - s["wall_ms"]) < 1e-9
        finally:
            obs.disable()

    def test_bench_plumbing_scaling_and_flat_ttft(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        from decode_bench import bench_serve_disagg
        r = bench_serve_disagg(preset="tiny", n_decode=2, max_batch=4,
                               n_requests=10,
                               prompt_lens=(24, 33, 28, 30),
                               max_new=24, page_size=8)
        assert r["handoffs"] > 0 and r["xfer_bytes"] > 0
        # decode throughput (busy-time projection) must SCALE with the
        # decode tier while the prefill tier — and so admitted TTFT —
        # is unchanged; generous noise bounds for the CPU plumbing run
        assert r["vs_1_decode"] >= 1.2, r
        assert r["ttft_p95_ms"] <= 3.0 * r["ttft_p95_1_decode_ms"], r


# ---------------------------------------------------------------------------
# server surface (the healthz/metrics role-visibility fix)
# ---------------------------------------------------------------------------

class TestServerDisagg:
    def test_healthz_and_metrics_report_roles_and_health(self,
                                                         tiny_llama):
        from paddle_tpu.serving.server import ServingServer
        ds, _p, _d = _disagg(tiny_llama, n_decode=2)
        srv = ServingServer(serving.FrontDoor(ds), port=0)
        host, port = srv.start()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            body = json.loads(r.read())
            assert r.status == 200 and body["status"] == "serving"
            assert [x["role"] for x in body["replicas"]] == \
                ["prefill", "decode", "decode"]
            assert all(x["healthy"] for x in body["replicas"])
            # a dead replica must flip the surface to degraded and name
            # the victim — before this fix the set answered healthy
            with srv._lock, warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                ds._fail_replica(2, RuntimeError("died"))
            conn.request("GET", "/healthz")
            body = json.loads(conn.getresponse().read())
            assert body["status"] == "degraded"
            assert body["replicas"][2] == dict(
                body["replicas"][2], healthy=False, role="decode")
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
            assert 'serve_replica_healthy{replica="2"} 0' in text
            assert 'serve_replica_healthy{replica="0"} 1' in text
            assert 'serve_replica_is_prefill{replica="0"} 1' in text
            assert "serve_degraded 1" in text
        finally:
            srv.close()

    def test_healthz_plain_engine_reports_role(self, tiny_llama):
        from paddle_tpu.serving.server import ServingServer
        eng = _engine(tiny_llama).warmup()
        srv = ServingServer(eng, port=0)
        host, port = srv.start()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            conn.request("GET", "/healthz")
            body = json.loads(conn.getresponse().read())
            assert body["status"] == "serving" and body["role"] == "both"
        finally:
            srv.close()
