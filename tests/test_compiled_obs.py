"""Compiled-artifact observability (docs/OBSERVABILITY.md "Reading the
roofline"): the CompiledArtifactLedger's capture contract, the analytic
roofline math, the new prom surfaces (serve.hbm.*, serve.roofline.*,
recompiles_total{site=...}), and the perf-regression ledger
(tools/bench_compare.py)."""

import importlib.util
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.observability as obs
from paddle_tpu.observability import _state as obs_state
from paddle_tpu.observability.compiled import (CHIP_SPECS, chip_spec,
                                               roofline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _always_disabled_after():
    yield
    obs.disable()


@pytest.fixture
def tiny_llama():
    from paddle_tpu.models.llama import llama
    pt.seed(0)
    return llama("tiny")


# -- ledger capture ----------------------------------------------------------

class TestLedgerCapture:
    def test_engine_warmup_full_ledger_zero_extra_compiles(self,
                                                           tiny_llama):
        """THE tentpole contract: warmup produces one ledger row per
        compiled program (row count == the sentinel's backend-compile
        count — the capture itself compiles NOTHING extra), rows carry
        cost/memory analysis with site attribution, and post-warmup
        serving stays at zero compiles with the jit caches at one
        entry, exactly the pre-ledger invariant."""
        from paddle_tpu import serving
        tel = obs.enable(crash_hooks=False)
        base = tel.sentinel.compiles()
        eng = serving.Engine(tiny_llama, num_blocks=32, page_size=8,
                             max_batch=2, max_seq_len=64).warmup()
        led = obs.get_ledger()
        assert led is tel.ledger is not None
        warmup_compiles = tel.sentinel.compiles() - base
        rows = led.snapshot()
        assert len(rows) == warmup_compiles > 0
        sites = {r["site"] for r in rows}
        assert {"serve.step", "serve.cow", "serve.swap"} <= sites
        step_rows = led.rows_for("serve.step")
        assert len(step_rows) == 1
        r = step_rows[0]
        # a real transformer step: nonzero flops, bytes, scratch, and
        # a measured compile wall
        assert r["flops"] > 0 and r["bytes_accessed"] > 0
        assert r["temp_bytes"] > 0 and r["compile_ms"] > 0
        assert r["peak_bytes"] > 0
        assert r["bound"] in ("compute", "bandwidth")
        assert r["min_ms"] > 0
        assert led.min_ms_for("serve.step") == pytest.approx(r["min_ms"])

        # serving traffic: zero additional compiles, zero new rows
        n0 = len(led.snapshot())
        c0 = tel.sentinel.compiles()
        eng.add_request(np.arange(5), max_new_tokens=4)
        while eng.has_work():
            eng.step()
        assert tel.sentinel.compiles() == c0
        assert len(led.snapshot()) == n0
        assert eng._step_fn._cache_size() == 1
        assert eng._cow_fn._cache_size() == 1

        # the hbm gauge block landed in the registry AND on the ledger
        snap = tel.registry.snapshot()
        hbm = led.hbm
        assert hbm["kv_pool_bytes"] == eng.kv.nbytes() > 0
        assert hbm["param_bytes"] > 0
        assert hbm["peak_temp_bytes"] == max(
            row["temp_bytes"] for row in rows)
        for k, v in hbm.items():
            assert snap[f"serve.hbm.{k}"] == v
        # roofline constants + measured-step attribution gauges
        assert snap["serve.roofline.step.min_ms"] > 0
        assert 0 < snap["serve.roofline.step.frac"] < 10
        assert ("serve.roofline.prefill.frac" in snap
                or "serve.roofline.decode.frac" in snap)

    def test_trainstep_first_compile_ledger(self, tiny_llama):
        import jax
        import jax.numpy as jnp
        from paddle_tpu import optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.llama import causal_lm_loss
        tel = obs.enable(crash_hooks=False)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=tiny_llama.parameters())
        step = TrainStep(tiny_llama, causal_lm_loss, opt)
        state = step.init_state(seed=0)
        ids = jax.random.randint(jax.random.key(0), (2, 16), 0,
                                 tiny_llama.cfg.vocab_size)
        batch = {"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)}
        state, m = step(state, batch)
        _ = float(m["loss"])
        led = obs.get_ledger()
        rows = led.rows_for(step._site)
        # the first call compiled the ONE step program, attributed to
        # the TrainStep site through timed_step's sentinel scope
        assert len(rows) == 1 and rows[0]["flops"] > 0
        n_rows = len(led.snapshot())
        # steady state: no new rows, and the post-warmup step publishes
        # the roofline attribution gauge for the site
        state, m = step(state, batch)
        _ = float(m["loss"])
        assert len(led.snapshot()) == n_rows
        snap = tel.registry.snapshot()
        frac = snap[f"train.roofline[{step._site}].frac"]
        # tiny cache-resident steps can beat the measured-CPU bandwidth
        # stand-in, so the frac may exceed 1 here — positive and sane
        # is the contract; exact math is pinned in TestRoofline
        assert 0 < frac < 100
        assert tel.monitor.last_event["roofline_frac"] == frac
        assert snap[f"train.roofline[{step._site}].min_ms"] > 0

    def test_disable_restores_compile_and_clears_hook(self):
        import jax
        import jax.numpy as jnp
        from jax._src.interpreters import pxla
        obs.enable(crash_hooks=False)
        assert obs_state.LEDGER[0] is not None
        assert pxla.MeshComputation.compile.__name__ == "_ledger_compile"
        obs.disable()
        assert obs_state.LEDGER[0] is None
        assert pxla.MeshComputation.compile.__name__ != "_ledger_compile"
        # compiles after disable land nowhere (no ledger, no crash)
        jax.jit(lambda x: x * 2)(jnp.ones((4,))).block_until_ready()

    def test_ledger_rows_reach_postmortem_and_sidecar(self, tmp_path):
        import jax
        import jax.numpy as jnp
        sink = obs.InMemorySink()
        tel = obs.enable(sinks=[sink], crash_hooks=False)
        with tel.sentinel.site("pm-site"):
            jax.jit(lambda x: (x @ x.T).sum())(
                jnp.ones((8, 8))).block_until_ready()
        obs.get_ledger().set_hbm({"kv_pool_bytes": 123})
        path = obs.write_postmortem(reason="test",
                                    path=str(tmp_path / "pm.jsonl"))
        lines = [json.loads(ln) for ln in
                 open(path).read().splitlines()]
        arts = [ln for ln in lines
                if ln.get("event") == "compiled_artifacts"]
        assert len(arts) == 1
        assert arts[0]["hbm"] == {"kv_pool_bytes": 123}
        assert any(r["site"] == "pm-site" and r["flops"] > 0
                   for r in arts[0]["rows"])
        # every capture also emitted one compiled_artifact event
        evs = sink.events("compiled_artifact")
        assert any(e["site"] == "pm-site" for e in evs)


# -- roofline math -----------------------------------------------------------

class TestRoofline:
    def test_hand_computed_bounds(self):
        spec = {"peak_flops": 100e12, "hbm_gbps": 1000.0}
        # compute-bound: 1e12 flops @ 100 TFLOP/s = 10 ms; 1 GB @
        # 1000 GB/s = 1 ms
        r = roofline(1e12, 1e9, spec)
        assert r["compute_ms"] == pytest.approx(10.0)
        assert r["memory_ms"] == pytest.approx(1.0)
        assert r["min_ms"] == pytest.approx(10.0)
        assert r["bound"] == "compute"
        # bandwidth-bound: 1e9 flops (0.01 ms) vs 10 GB (10 ms)
        r = roofline(1e9, 1e10, spec)
        assert r["min_ms"] == pytest.approx(10.0)
        assert r["bound"] == "bandwidth"
        # the ridge: ties classify as compute
        r = roofline(100e9, 1e9, spec)
        assert r["bound"] == "compute"

    def test_chip_spec_table_and_override(self):
        v4 = chip_spec("TPU v4")
        assert v4["peak_flops"] == 275e12 and v4["hbm_gbps"] == 1228.0
        v5e = chip_spec("TPU v5 lite chip")   # prefix match
        assert v5e["peak_flops"] == 197e12
        # v5p must not be swallowed by the shorter "TPU v5" prefix
        assert chip_spec("TPU v5p")["hbm_gbps"] == 2765.0
        unknown = chip_spec("FancyChip 9000")
        assert unknown["peak_flops"] == CHIP_SPECS["cpu"]["peak_flops"]
        ov = chip_spec("TPU v4", override={"hbm_gbps": 999.0})
        assert ov["hbm_gbps"] == 999.0 and ov["peak_flops"] == 275e12
        # CPU stand-in is measured, positive, sane
        cpu = chip_spec("cpu")
        assert 1.0 <= cpu["hbm_gbps"] <= 1000.0

    def test_flops_column_pinned_to_mfu_table(self):
        # ONE source of truth for peak flops: compiled.py's chip table
        # must agree with mfu.PEAK_BF16_FLOPS wherever both know a chip
        from paddle_tpu.observability.mfu import PEAK_BF16_FLOPS
        for kind, spec in CHIP_SPECS.items():
            if kind in PEAK_BF16_FLOPS:
                assert spec["peak_flops"] == PEAK_BF16_FLOPS[kind], kind


# -- prom surface ------------------------------------------------------------

class TestPromSurface:
    def test_recompiles_total_labeled_counter(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.observability.sinks import registry_to_prometheus
        tel = obs.enable(crash_hooks=False)
        with tel.sentinel.site("site=[a,b]"):    # reserved chars squash
            jax.jit(lambda x: x + 1)(jnp.ones((3,))).block_until_ready()
        text = registry_to_prometheus(tel.registry)
        assert '# TYPE recompiles_total counter' in text
        m = re.search(r'recompiles_total\{site="site__a_b_"\} (\d+)',
                      text)
        assert m and int(m.group(1)) >= 1

    def test_hbm_and_roofline_gauges_roundtrip_fleet_fold(self):
        from paddle_tpu.observability.aggregate import (fleet_fold,
                                                        registry_to_wire)
        from paddle_tpu.observability.sinks import registry_to_prometheus
        reg = obs.MetricsRegistry()
        reg.gauge("serve.hbm.kv_pool_bytes").set(4096)
        reg.gauge("serve.roofline.step.min_ms").set(0.5)
        reg.counter("recompiles_total[site=serve.step]").inc(3)
        # local surface
        text = registry_to_prometheus(reg)
        assert "serve_hbm_kv_pool_bytes 4096" in text
        assert "serve_roofline_step_min_ms 0.5" in text
        assert 'recompiles_total{site="serve.step"} 3' in text
        # fleet surface: wire → fold → per-worker labels + rollup
        fleet = fleet_fold({"w0": {"role": "decode",
                                   "metrics": registry_to_wire(reg)}})
        ftext = registry_to_prometheus(fleet)
        assert ('serve_hbm_kv_pool_bytes{worker="w0",role="decode"} 4096'
                in ftext)
        assert 'recompiles_total{site="serve.step",worker="w0"' in ftext

    def test_worker_snapshot_hbm_block_folds_to_cluster_metrics(self):
        from paddle_tpu.serving.cluster import ClusterController

        class _Store:
            def __init__(self):
                self.kv = {}

            def set(self, k, v):
                self.kv[k] = v

            def get(self, k):
                return self.kv.get(k)

            def add(self, k, n):
                cur = int(self.kv.get(k, b"0")) + n
                self.kv[k] = str(cur).encode()
                return cur

            def delete(self, k):
                return self.kv.pop(k, None) is not None

            def compare_set(self, k, expected, new):
                if self.kv.get(k) == expected or (
                        expected in (b"", None) and k not in self.kv):
                    self.kv[k] = new
                    return True
                return False

            def keys(self, pfx):
                return [k for k in self.kv if k.startswith(pfx)]

        store = _Store()
        ctl = ClusterController(store)
        store.set("cluster/workers/w0", json.dumps(
            {"worker": "w0", "role": "decode", "epoch": 0,
             "version": "v0"}).encode())
        store.set("cluster/telemetry/w0", json.dumps(
            {"worker": "w0", "role": "decode", "metrics": {},
             "hbm": {"kv_pool_bytes": 8192,
                     "param_bytes": 1024}}).encode())
        text = ctl.metrics_text()
        assert ('serve_hbm_kv_pool_bytes{worker="w0",role="decode"} 8192'
                in text)
        assert ('serve_hbm_param_bytes{worker="w0",role="decode"} 1024'
                in text)


# -- standalone-load contract ------------------------------------------------

def test_compiled_module_loads_standalone():
    """compiled.py is importable with no package, no jax imported at
    module scope — the aggregate.py/sinks.py contract for offline
    tools."""
    path = os.path.join(REPO, "paddle_tpu", "observability",
                        "compiled.py")
    spec = importlib.util.spec_from_file_location("_compiled_sa", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    r = mod.roofline(1e12, 1e9, {"peak_flops": 100e12,
                                 "hbm_gbps": 1000.0})
    assert r["bound"] == "compute"
    led = mod.CompiledArtifactLedger()
    assert led.snapshot() == [] and led.min_ms_for("x") is None

    class _Exec:
        def cost_analysis(self):
            return [{"flops": 2e9, "bytes accessed": 1e6}]

        def memory_analysis(self):
            class _MA:
                argument_size_in_bytes = 100
                output_size_in_bytes = 50
                temp_size_in_bytes = 30
                alias_size_in_bytes = 20
                generated_code_size_in_bytes = 10
            return _MA()

    row = led.record_executable(_Exec(), program="jit(x)",
                                compile_ms=5.0)
    assert row["flops"] == 2e9 and row["argument_bytes"] == 100
    assert row["peak_bytes"] == 100 + 50 + 30 + 10 - 20
    assert len(led) == 1


def test_bench_compare_loads_standalone():
    path = os.path.join(REPO, "tools", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("_bc_sa", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.direction("serve_cpu_tok_s") == "higher"
    assert mod.direction("ms_per_step") == "lower"
    assert mod.direction("loss") is None


# -- perf-regression ledger (tools/bench_compare.py) -------------------------

def _load_bench_compare():
    path = os.path.join(REPO, "tools", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("_bc_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchCompare:
    def _round(self, n, ms, tok_s, provenance=None):
        extra = {"ms_per_step": ms, "serve_cpu_tok_s": tok_s,
                 "loss": 5.0, "serve_detail": {"requests": 6},
                 "window_ms_per_step": [ms, ms * 1.1]}
        if provenance is not None:
            extra["provenance"] = provenance
        return {"n": n, "cmd": "bench", "rc": 0, "tail": "",
                "parsed": {"metric": "llama_train_mfu", "value": 0.4,
                           "unit": "mfu_fraction", "extra": extra}}

    def test_trajectory_fold_with_sidecar_and_backfill(self, tmp_path):
        bc = _load_bench_compare()
        # two driver rounds (r01 has NO provenance → backfilled) + one
        # sidecar round
        p1 = tmp_path / "BENCH_r01.json"
        p1.write_text(json.dumps(self._round(1, 100.0, 50.0)))
        p2 = tmp_path / "BENCH_r02.json"
        p2.write_text(json.dumps(self._round(
            2, 80.0, 60.0, provenance={"git_sha": "abc123",
                                       "jax": "0.4.37"})))
        side = tmp_path / "bench_telemetry.jsonl"
        side.write_text(
            json.dumps({"event": "run_meta"}) + "\n" +
            json.dumps({"event": "bench_result",
                        **self._round(3, 90.0, 55.0)["parsed"]}) + "\n")
        rounds = []
        for p in (p1, p2, side):
            rounds.extend(bc.load_round(str(p)))
        assert [r["label"] for r in rounds] == \
            ["r01", "r02", "bench_telemetry.jsonl"]
        assert rounds[0]["provenance"]["git_sha"] is None  # backfilled
        assert rounds[1]["provenance"]["git_sha"] == "abc123"
        table = bc.fold_trajectory(rounds, baseline={
            "rows": {"ms_per_step": {"value": 100.0}}})
        ent = table["ms_per_step"]
        assert [v for _, v in ent["series"]] == [100.0, 80.0, 90.0]
        assert ent["best"] == 80.0 and ent["last"] == 90.0
        # lower-better: 90 vs baseline 100 is 10% BETTER
        assert ent["delta_vs_baseline"] == pytest.approx(0.1)
        assert table["serve_cpu_tok_s"]["best"] == 60.0
        # nested detail dicts and window lists never become rows
        assert "serve_detail" not in table
        assert "window_ms_per_step" not in table
        md = bc.render_md(table)
        assert "| `serve_cpu_tok_s` |" in md

    def test_regression_detection_and_noise_band(self):
        bc = _load_bench_compare()
        baseline = {"rows": {
            "serve_cpu_tok_s": {"value": 50.0, "band": 0.4,
                                "better": "higher"},
            "ms_per_step": {"value": 100.0, "band": 0.4,
                            "better": "lower"}}}
        # within-band noise (−10% tok/s, +10% ms) passes
        ok, _ = bc.check({"serve_cpu_tok_s": 45.0, "ms_per_step": 110.0},
                         baseline)
        assert ok
        # injected 2× slowdown is flagged
        ok, lines = bc.check({"serve_cpu_tok_s": 25.0,
                              "ms_per_step": 100.0}, baseline)
        assert not ok
        assert any("REGRESSION" in ln and "serve_cpu_tok_s" in ln
                   for ln in lines)
        ok, _ = bc.check({"serve_cpu_tok_s": 50.0, "ms_per_step": 200.0},
                         baseline)
        assert not ok
        # a row the fresh run lacks skips, never fails
        ok, lines = bc.check({"ms_per_step": 100.0}, baseline)
        assert ok and any("skip" in ln for ln in lines)
        # improvements never trip the gate
        ok, _ = bc.check({"serve_cpu_tok_s": 500.0, "ms_per_step": 10.0},
                         baseline)
        assert ok

    def test_check_cli_exit_codes_against_committed_baseline(
            self, tmp_path):
        """The acceptance contract end-to-end: --check exits 0 on the
        committed seed numbers and nonzero on a 2× CPU-plumbing
        slowdown, through the real CLI against the real baseline."""
        baseline_path = os.path.join(REPO, "tools",
                                     "bench_baseline.json")
        rows = json.load(open(baseline_path))["rows"]
        gated = {k: s for k, s in rows.items()
                 if s.get("better") in ("higher", "lower")}
        assert gated, "committed baseline must carry gateable rows"
        seed = {"metric": "llama_train_mfu",
                "value": rows.get("llama_train_mfu",
                                  {}).get("value", 0.0),
                "unit": "mfu_fraction",
                "extra": {k: s["value"] for k, s in rows.items()
                          if k != "llama_train_mfu"}}
        slow = json.loads(json.dumps(seed))
        victim = sorted(gated)[0]
        spec_ = gated[victim]
        tgt = slow["extra"] if victim in slow["extra"] else slow
        key = victim if victim in slow["extra"] else "value"
        tgt[key] = (spec_["value"] / 2.0
                    if spec_["better"] == "higher"
                    else spec_["value"] * 2.0)
        rcs = {}
        for name, payload in (("seed", seed), ("slow", slow)):
            p = tmp_path / f"{name}.json"
            p.write_text(json.dumps(payload))
            r = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "bench_compare.py"),
                 "--check", "--fresh", str(p)],
                capture_output=True, text=True, timeout=60)
            rcs[name] = r.returncode
        assert rcs["seed"] == 0
        assert rcs["slow"] != 0

    def test_check_skips_on_backend_mismatch(self, tmp_path):
        """Row NAMES are shared across platforms but scales are not: a
        TPU fresh run against the CPU baseline gates nothing instead of
        failing everything."""
        p = tmp_path / "tpu.json"
        p.write_text(json.dumps(
            {"metric": "llama_train_mfu", "value": 0.52,
             "unit": "mfu_fraction",
             "extra": {"ms_per_step": 203.0,
                       "provenance": {"backend": "tpu",
                                      "git_sha": "abc"}}}))
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "bench_compare.py"),
             "--check", "--fresh", str(p)],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0
        assert "backend mismatch" in r.stdout


# -- bench provenance --------------------------------------------------------

def test_bench_provenance_block():
    sys.path.insert(0, REPO)
    try:
        import bench
        prov = bench.provenance("off")
        assert prov["fused"] == "off"
        assert prov["jax"] and prov["backend"]
        assert "device" in prov
        # git_sha resolves in a checkout (this repo is one)
        assert prov["git_sha"] is None or len(prov["git_sha"]) >= 7
    finally:
        sys.path.remove(REPO)
