"""nn breadth tests: conv variants vs torch oracle, RNN/LSTM/GRU scan
correctness, transformer decoder, SDXL UNet train step."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

R = np.random.default_rng(3)


def A(*shape):
    return R.normal(size=shape).astype("float32")


class TestConvOracle:
    def test_conv1d(self):
        x, w, b = A(2, 3, 16), A(5, 3, 4), A(5)
        got = np.asarray(F.conv1d(x, w, b, stride=2, padding=1))
        want = TF.conv1d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                         stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv3d(self):
        x, w = A(1, 2, 6, 6, 6), A(4, 2, 3, 3, 3)
        got = np.asarray(F.conv3d(x, w, stride=1, padding=1))
        want = TF.conv3d(torch.tensor(x), torch.tensor(w), padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_strided(self):
        x, w, b = A(2, 3, 8, 8), A(3, 5, 4, 4), A(5)
        got = np.asarray(F.conv2d_transpose(x, w, b, stride=2, padding=1,
                                            output_padding=1))
        want = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                   torch.tensor(b), stride=2, padding=1,
                                   output_padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_groups(self):
        x, w = A(1, 4, 5, 5), A(4, 3, 3, 3)
        got = np.asarray(F.conv2d_transpose(x, w, stride=2, groups=2))
        want = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                   stride=2, groups=2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_instance_norm(self):
        x, w, b = A(2, 3, 5, 5), A(3), A(3)
        got = np.asarray(F.instance_norm(x, w, b))
        want = TF.instance_norm(torch.tensor(x), weight=torch.tensor(w),
                                bias=torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_adaptive_pools_nondivisible(self):
        x = A(1, 2, 7, 5)
        got = np.asarray(F.adaptive_avg_pool2d(x, (3, 2)))
        want = TF.adaptive_avg_pool2d(torch.tensor(x), (3, 2)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        got = np.asarray(F.adaptive_max_pool2d(x, (3, 2)))
        want = TF.adaptive_max_pool2d(torch.tensor(x), (3, 2)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_pixel_shuffle_roundtrip(self):
        x = A(2, 8, 3, 3)
        up = F.pixel_shuffle(x, 2)
        want = TF.pixel_shuffle(torch.tensor(x), 2).numpy()
        np.testing.assert_allclose(np.asarray(up), want, rtol=1e-6)
        back = F.pixel_unshuffle(up, 2)
        np.testing.assert_allclose(np.asarray(back), x, rtol=1e-6)

    def test_pool1d(self):
        x = A(2, 3, 12)
        got = np.asarray(F.avg_pool1d(x, 3, stride=2, padding=1))
        want = TF.avg_pool1d(torch.tensor(x), 3, stride=2, padding=1,
                             count_include_pad=False).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestConvLayers:
    def test_layer_shapes(self):
        pt.seed(0)
        assert nn.Conv1D(3, 8, 3, padding=1)(A(2, 3, 10)).shape == (2, 8, 10)
        assert nn.Conv3D(2, 4, 3, padding=1)(A(1, 2, 4, 4, 4)).shape == (1, 4, 4, 4, 4)
        assert nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)(A(1, 3, 8, 8)).shape == (1, 6, 16, 16)
        assert nn.InstanceNorm2D(3)(A(2, 3, 5, 5)).shape == (2, 3, 5, 5)
        assert nn.AdaptiveAvgPool2D(1)(A(2, 3, 7, 7)).shape == (2, 3, 1, 1)
        assert nn.PixelShuffle(2)(A(1, 8, 4, 4)).shape == (1, 2, 8, 8)
        assert nn.PReLU(4)(A(2, 4, 3, 3)).shape == (2, 4, 3, 3)

    def test_losses(self):
        p = np.abs(A(8)) / 2 + 0.1
        l = (A(8) > 0).astype("float32")
        got = float(nn.BCELoss()(pt.to_tensor(np.clip(p, 0, 1)), pt.to_tensor(l)))
        want = float(TF.binary_cross_entropy(torch.tensor(np.clip(p, 0, 1)),
                                             torch.tensor(l)))
        assert abs(got - want) < 1e-4
        x, y = A(4, 6), A(4, 6)
        got = float(nn.SmoothL1Loss()(pt.to_tensor(x), pt.to_tensor(y)))
        want = float(TF.smooth_l1_loss(torch.tensor(x), torch.tensor(y)))
        assert abs(got - want) < 1e-4
        logp = np.log(np.abs(A(4, 6)) / 10 + 0.01)
        tgt = np.abs(A(4, 6)); tgt = tgt / tgt.sum()
        got = float(nn.KLDivLoss()(pt.to_tensor(logp), pt.to_tensor(tgt)))
        want = float(TF.kl_div(torch.tensor(logp), torch.tensor(tgt)))
        assert abs(got - want) < 1e-4


class TestRNN:
    def _torch_lstm(self, x, jx_lstm, bidirectional=False, layers=1):
        tl = torch.nn.LSTM(x.shape[-1], jx_lstm.hidden_size,
                           num_layers=layers, batch_first=True,
                           bidirectional=bidirectional)
        # copy our params into torch
        ndir = 2 if bidirectional else 1
        for layer in range(layers):
            for d in range(ndir):
                suffix = "_reverse" if d else ""
                cell = getattr(jx_lstm, f"cell_{layer}{suffix}")
                getattr(tl, f"weight_ih_l{layer}{suffix}").data = \
                    torch.tensor(np.asarray(cell.weight_ih))
                getattr(tl, f"weight_hh_l{layer}{suffix}").data = \
                    torch.tensor(np.asarray(cell.weight_hh))
                getattr(tl, f"bias_ih_l{layer}{suffix}").data = \
                    torch.tensor(np.asarray(cell.bias_ih))
                getattr(tl, f"bias_hh_l{layer}{suffix}").data = \
                    torch.tensor(np.asarray(cell.bias_hh))
        return tl

    def test_lstm_vs_torch(self):
        pt.seed(0)
        x = A(2, 7, 5)
        m = nn.LSTM(5, 6)
        out, (h, c) = m(x)
        tl = self._torch_lstm(x, m)
        want, (th, tc) = tl(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(h), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c), tc.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_bidirectional_two_layers(self):
        pt.seed(1)
        x = A(3, 5, 4)
        m = nn.LSTM(4, 3, num_layers=2, direction="bidirect")
        out, (h, c) = m(x)
        assert out.shape == (3, 5, 6)
        assert h.shape == (4, 3, 3)
        tl = self._torch_lstm(x, m, bidirectional=True, layers=2)
        want, _ = tl(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), want.detach().numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_gru_vs_torch(self):
        pt.seed(2)
        x = A(2, 6, 4)
        m = nn.GRU(4, 5)
        out, h = m(x)
        tg = torch.nn.GRU(4, 5, batch_first=True)
        cell = m.cell_0
        tg.weight_ih_l0.data = torch.tensor(np.asarray(cell.weight_ih))
        tg.weight_hh_l0.data = torch.tensor(np.asarray(cell.weight_hh))
        tg.bias_ih_l0.data = torch.tensor(np.asarray(cell.bias_ih))
        tg.bias_hh_l0.data = torch.tensor(np.asarray(cell.bias_hh))
        want, th = tg(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_simple_rnn_shapes_and_grad(self):
        import jax
        from paddle_tpu.nn.layer import functional_call, raw_params
        pt.seed(3)
        m = nn.SimpleRNN(4, 5, num_layers=2)
        x = A(2, 6, 4)
        out, h = m(x)
        assert out.shape == (2, 6, 5) and h.shape == (2, 2, 5)
        p = raw_params(m)
        g = jax.grad(lambda p: functional_call(m, p, x)[0].sum())(p)
        assert all(np.isfinite(np.asarray(v)).all() for v in g.values())


class TestRNNFixes:
    def test_sequence_length_masks_padding(self):
        pt.seed(5)
        m = nn.LSTM(4, 3)
        x = A(2, 6, 4)
        seq_len = np.array([6, 3])
        out, (h, c) = m(x, sequence_length=pt.to_tensor(seq_len))
        # outputs past each length are zero
        assert np.abs(np.asarray(out[1, 3:])).max() == 0
        assert np.abs(np.asarray(out[1, :3])).max() > 0
        # final state of the short sequence == running it unpadded
        out_s, (h_s, _) = m(x[1:2, :3])
        np.testing.assert_allclose(np.asarray(h[0, 1]), np.asarray(h_s[0, 0]),
                                   rtol=1e-5, atol=1e-6)

    def test_interlayer_dropout_applied(self):
        pt.seed(6)
        m = nn.GRU(4, 4, num_layers=2, dropout=0.9)
        x = A(2, 5, 4)
        m.eval()
        out_eval, _ = m(x)
        m.train()
        out_train, _ = m(x)
        # with dropout 0.9 between layers, train output must differ from eval
        assert np.abs(np.asarray(out_eval) - np.asarray(out_train)).max() > 1e-4

    def test_state_dict_reference_naming(self):
        pt.seed(7)
        m = nn.LSTM(4, 3, num_layers=2, direction="bidirect")
        sd = m.state_dict()
        assert "weight_ih_l0" in sd and "weight_hh_l1_reverse" in sd
        m2 = nn.LSTM(4, 3, num_layers=2, direction="bidirect")
        m2.set_state_dict(sd)
        x = A(1, 4, 4)
        np.testing.assert_allclose(np.asarray(m(x)[0]), np.asarray(m2(x)[0]),
                                   rtol=1e-6)


class TestLayerFixes:
    def test_transformer_layers_fresh_init(self):
        pt.seed(8)
        proto = nn.TransformerEncoderLayer(8, 2, 16)
        enc = nn.TransformerEncoder(proto, 2)
        assert enc.layers[0] is proto  # prototype becomes layer 0 (paddle)
        w0 = np.asarray(enc.layers[0].linear1.weight)
        w1 = np.asarray(enc.layers[1].linear1.weight)
        assert np.abs(w0 - w1).max() > 1e-4  # NOT byte-identical

    def test_transformer_encoder_subclass_prototype(self):
        class MyLayer(nn.TransformerEncoderLayer):
            def __init__(self, d_model, extra):
                super().__init__(d_model, 2, 16)
                self.extra = extra

        pt.seed(9)
        enc = nn.TransformerEncoder(MyLayer(8, "x"), 2)  # must not crash
        assert len(enc.layers) == 2 and enc.layers[1].extra == "x"

    def test_conv_transpose_same_padding(self):
        x, w = A(1, 3, 8, 8), A(3, 5, 3, 3)
        out = F.conv2d_transpose(x, w, stride=2, padding="SAME")
        assert out.shape == (1, 5, 16, 16)
        with pytest.raises(NotImplementedError):
            F.conv2d_transpose(A(1, 4, 8, 8), A(4, 2, 3, 3),
                               padding="SAME", groups=2)

    def test_instance_norm1d_nlc(self):
        x = A(2, 6, 3)  # NLC: channels last
        m = nn.InstanceNorm1D(3, data_format="NLC")
        out = np.asarray(m(x))
        # normalized over L per channel: mean≈0 along axis 1
        assert np.abs(out.mean(axis=1)).max() < 1e-5


class TestTransformerDecoder:
    def test_decoder_and_full_transformer(self):
        pt.seed(0)
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        model.eval()
        src, tgt = A(2, 7, 16), A(2, 5, 16)
        mask = nn.Transformer.generate_square_subsequent_mask(5)
        out = model(src, tgt, tgt_mask=mask)
        assert out.shape == (2, 5, 16)
        # causality: future tgt positions must not affect earlier outputs
        tgt2 = tgt.copy()
        tgt2[:, -1] += 100.0
        out2 = model(src, pt.to_tensor(tgt2), tgt_mask=mask)
        np.testing.assert_allclose(np.asarray(out[:, :4]),
                                   np.asarray(out2[:, :4]), atol=1e-4)


class TestSDXLUNet:
    def test_tiny_unet_trains(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models.sdxl_unet import sdxl_unet
        from paddle_tpu.nn.layer import functional_call, raw_params
        from paddle_tpu.optimizer import AdamW

        pt.seed(0)
        m = sdxl_unet("tiny")
        x = jnp.asarray(A(2, 4, 16, 16))
        t = jnp.array([3, 777])
        ctx = jnp.asarray(A(2, 6, 64))
        ac = jnp.asarray(A(2, 96))
        eps = jnp.asarray(A(2, 4, 16, 16))

        out = m(x, t, ctx, ac)
        assert out.shape == x.shape

        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        params = raw_params(m)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                pred = functional_call(m, p, x, t, ctx, ac, training=True)
                return ((pred - eps) ** 2).mean()
            loss, g = jax.value_and_grad(loss_fn)(params)
            params, state = opt.apply(g, state, params)
            return params, state, loss

        losses = []
        for _ in range(8):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_unet_no_added_cond_preset(self):
        import jax.numpy as jnp
        from paddle_tpu.models.sdxl_unet import SDXLUNet, UNetConfig
        pt.seed(0)
        cfg = UNetConfig(block_out_channels=(16, 32), layers_per_block=1,
                         transformer_depth=(0, 1), num_attention_heads=(2, 2),
                         cross_attention_dim=32, norm_num_groups=8,
                         projection_class_embeddings_input_dim=0)
        m = SDXLUNet(cfg)
        out = m(jnp.zeros((1, 4, 8, 8)), jnp.array([5]),
                jnp.zeros((1, 3, 32)))
        assert out.shape == (1, 4, 8, 8)


class TestSpatialSampling:
    """grid_sample / affine_grid / fold vs the torch oracle."""

    def _torch(self):
        import torch
        return torch

    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
    @pytest.mark.parametrize("align", [True, False])
    def test_grid_sample_matches_torch(self, mode, pad, align):
        torch = self._torch()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 3, 5, 7)).astype(np.float32)
        grid = rng.uniform(-1.3, 1.3, size=(2, 4, 6, 2)).astype(np.float32)
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), mode=mode,
            padding_mode=pad, align_corners=align).numpy()
        got = np.asarray(F.grid_sample(jnp.asarray(x), jnp.asarray(grid),
                                       mode=mode, padding_mode=pad,
                                       align_corners=align))
        if mode == "nearest":
            # ties at .5 can round differently; compare off-tie fraction
            close = np.isclose(got, ref, atol=1e-5)
            assert close.mean() > 0.97, close.mean()
        else:
            np.testing.assert_allclose(got, ref, atol=1e-5)

    @pytest.mark.parametrize("align", [True, False])
    def test_affine_grid_matches_torch(self, align):
        torch = self._torch()
        theta = np.array([[[0.8, 0.1, 0.2], [-0.1, 1.1, -0.3]],
                          [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]], np.float32)
        ref = torch.nn.functional.affine_grid(
            torch.tensor(theta), size=(2, 3, 5, 7),
            align_corners=align).numpy()
        got = np.asarray(F.affine_grid(jnp.asarray(theta), (2, 3, 5, 7),
                                       align_corners=align))
        np.testing.assert_allclose(got, ref, atol=1e-6)

    def test_fold_inverts_unfold(self):
        torch = self._torch()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 3, 8, 10)).astype(np.float32)
        cols = np.asarray(F.unfold(jnp.asarray(x), 3, strides=2, paddings=1))
        ref = torch.nn.functional.fold(
            torch.tensor(cols), output_size=(8, 10), kernel_size=3,
            stride=2, padding=1).numpy()
        got = np.asarray(F.fold(jnp.asarray(cols), (8, 10), 3, strides=2,
                                paddings=1))
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_upsample_alias(self):
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        a = F.upsample(x, scale_factor=2, mode="nearest")
        b = F.interpolate(x, scale_factor=2, mode="nearest")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestLossLongTail:
    def test_ctc_loss_matches_torch(self):
        torch.manual_seed(0)
        T, B, C, L = 12, 3, 5, 4
        logits = torch.randn(T, B, C).log_softmax(-1)
        labels = torch.randint(1, C, (B, L))
        in_len = torch.tensor([12, 10, 8])
        lb_len = torch.tensor([4, 3, 2])
        ref = TF.ctc_loss(logits, labels, in_len, lb_len, blank=0,
                          reduction="mean", zero_infinity=False)
        got = F.ctc_loss(jnp.asarray(logits.numpy()),
                         jnp.asarray(labels.numpy()),
                         jnp.asarray(in_len.numpy()),
                         jnp.asarray(lb_len.numpy()), blank=0)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)

    def test_huber_matches_torch(self):
        x = np.random.default_rng(0).normal(size=(8,)).astype(np.float32)
        y = np.zeros((8,), np.float32)
        ref = TF.huber_loss(torch.tensor(x), torch.tensor(y), delta=0.7)
        got = F.huber_loss(jnp.asarray(x), jnp.asarray(y), delta=0.7)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)

    def test_triplet_and_cosine_and_hinge(self):
        rng = np.random.default_rng(1)
        a, p_, n = (jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
                    for _ in range(3))
        ref = TF.triplet_margin_loss(torch.tensor(np.asarray(a)),
                                     torch.tensor(np.asarray(p_)),
                                     torch.tensor(np.asarray(n)))
        got = F.triplet_margin_loss(a, p_, n)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)

        lbl = jnp.asarray([1.0, -1.0, 1.0, -1.0])
        ref = TF.cosine_embedding_loss(torch.tensor(np.asarray(a)),
                                       torch.tensor(np.asarray(p_)),
                                       torch.tensor(np.asarray(lbl)))
        got = F.cosine_embedding_loss(a, p_, lbl)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

        x1 = a[:, 0]
        ref = TF.hinge_embedding_loss(torch.tensor(np.asarray(x1)),
                                      torch.tensor(np.asarray(lbl)))
        got = F.hinge_embedding_loss(x1, lbl)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


class TestLayersMoreRound2:
    def _x4(self):
        return jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 4, 8, 8)).astype(np.float32))

    def test_upsampling_bilinear_align_corners_vs_torch(self):
        x = self._x4()
        ours = np.asarray(nn.UpsamplingBilinear2D(size=[16, 16])(x))
        ref = TF.interpolate(torch.tensor(np.asarray(x)), size=(16, 16),
                             mode="bilinear", align_corners=True).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_local_response_norm_vs_torch(self):
        x = self._x4()
        ours = np.asarray(nn.LocalResponseNorm()(x))
        ref = TF.local_response_norm(torch.tensor(np.asarray(x)), 5,
                                     alpha=1e-4, beta=0.75, k=1.0).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-6)

    def test_max_unpool2d_roundtrip_vs_torch(self):
        x = self._x4()
        pooled, idx = TF.max_pool2d(torch.tensor(np.asarray(x)), 2,
                                    return_indices=True)
        ours = nn.MaxUnPool2D(2)(jnp.asarray(pooled.numpy()),
                                 jnp.asarray(idx.numpy()))
        ref = TF.max_unpool2d(pooled, idx, 2).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-6)

    def test_channel_shuffle_vs_torch(self):
        x = self._x4()
        ours = np.asarray(nn.ChannelShuffle(2)(x))
        ref = torch.channel_shuffle(torch.tensor(np.asarray(x)), 2).numpy()
        np.testing.assert_allclose(ours, ref)

    def test_bilinear_vs_torch(self):
        torch.manual_seed(0)
        tb = torch.nn.Bilinear(5, 6, 3)
        ours = nn.Bilinear(5, 6, 3)
        ours.weight = jnp.asarray(tb.weight.detach().numpy())
        ours.bias = jnp.asarray(tb.bias.detach().numpy())
        x1 = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
        x2 = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(ours(jnp.asarray(x1), jnp.asarray(x2))),
            tb(torch.tensor(x1), torch.tensor(x2)).detach().numpy(),
            rtol=1e-4, atol=1e-5)

    def test_pairwise_distance_vs_torch(self):
        x = np.random.default_rng(0).normal(size=(4, 7)).astype(np.float32)
        y = np.random.default_rng(1).normal(size=(4, 7)).astype(np.float32)
        for p in (1.0, 2.0):
            np.testing.assert_allclose(
                np.asarray(nn.PairwiseDistance(p=p)(jnp.asarray(x),
                                                    jnp.asarray(y))),
                TF.pairwise_distance(torch.tensor(x), torch.tensor(y),
                                     p=p).numpy(), rtol=1e-4, atol=1e-5)

    def test_pad_family_and_misc_shapes(self):
        x = self._x4()
        assert nn.Pad1D([1, 2])(jnp.ones((2, 3, 5))).shape == (2, 3, 8)
        assert nn.Pad3D([1, 1, 1, 1, 1, 1])(
            jnp.ones((1, 2, 3, 4, 5))).shape == (1, 2, 5, 6, 7)
        assert nn.ZeroPad2D([1, 2, 3, 4])(x).shape == (2, 4, 15, 11)
        assert nn.Unflatten(1, [2, 2])(x).shape == (2, 2, 2, 8, 8)
        assert nn.Softmax2D()(x).shape == x.shape
        np.testing.assert_allclose(
            np.asarray(nn.Softmax2D()(x).sum(axis=1)), 1.0, rtol=1e-5)
        assert nn.AdaptiveMaxPool1D(3)(jnp.ones((2, 3, 9))).shape == (2, 3, 3)
        assert nn.SyncBatchNorm(4)(x).shape == x.shape
        assert nn.SyncBatchNorm.convert_sync_batchnorm(nn.Linear(2, 2))

    def test_alpha_dropout_preserves_moments(self):
        ad = nn.AlphaDropout(0.25)
        ad.train()
        import paddle_tpu as pt
        pt.seed(0)
        x = jnp.asarray(np.random.default_rng(2).normal(
            size=(20000,)).astype(np.float32))
        out = np.asarray(ad(x))
        assert abs(out.mean() - np.asarray(x).mean()) < 0.05
        assert abs(out.std() - np.asarray(x).std()) < 0.1

    def test_activation_layer_batch(self):
        x = jnp.linspace(-3, 3, 13)
        for layer, fn in ((nn.SELU(), TF.selu), (nn.CELU(1.0), TF.celu),
                          (nn.Tanhshrink(), TF.tanhshrink),
                          (nn.LogSigmoid(), TF.logsigmoid),
                          (nn.Hardshrink(), TF.hardshrink),
                          (nn.Softshrink(), TF.softshrink)):
            np.testing.assert_allclose(
                np.asarray(layer(x)),
                fn(torch.tensor(np.asarray(x))).numpy(),
                rtol=1e-4, atol=1e-6)
        glu = nn.GLU()(jnp.asarray(np.random.default_rng(3).normal(
            size=(2, 8)).astype(np.float32)))
        assert glu.shape == (2, 4)
