"""NumPy-oracle tests for functional ops (reference OpTest pattern:
test/legacy_test/op_test.py — declare inputs, compare against NumPy impl,
check grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.nn import functional as F


def test_basic_ops_namespace():
    x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(np.asarray(pt.sum(x)), 10.0)
    np.testing.assert_allclose(np.asarray(pt.mean(x, axis=0)), [2.0, 3.0])
    np.testing.assert_allclose(np.asarray(pt.matmul(x, x, transpose_y=True)),
                               np.asarray(x) @ np.asarray(x).T)
    y = pt.concat([x, x], axis=1)
    assert y.shape == (2, 4)
    parts = pt.split(y, [1, -1], axis=1)
    assert parts[0].shape == (2, 1) and parts[1].shape == (2, 3)
    assert pt.topk(x, 1)[0].shape == (2, 1)
    np.testing.assert_allclose(np.asarray(pt.flatten(x)), [1, 2, 3, 4])


def test_layer_norm_oracle(rng):
    x = rng.standard_normal((4, 10)).astype(np.float32)
    w = rng.standard_normal(10).astype(np.float32)
    b = rng.standard_normal(10).astype(np.float32)
    out = F.layer_norm(jnp.asarray(x), (10,), jnp.asarray(w), jnp.asarray(b))
    mu, var = x.mean(-1, keepdims=True), x.var(-1, keepdims=True)
    expect = (x - mu) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_rms_norm_oracle(rng):
    x = rng.standard_normal((4, 16)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    out = F.rms_norm(jnp.asarray(x), jnp.asarray(w), 1e-6)
    expect = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_softmax_cross_entropy_oracle(rng):
    logits = rng.standard_normal((6, 5)).astype(np.float32)
    labels = rng.integers(0, 5, size=(6,))
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = -np.log(p[np.arange(6), labels]).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-4)


def test_cross_entropy_ignore_index(rng):
    logits = rng.standard_normal((4, 5)).astype(np.float32)
    labels = np.array([1, -100, 2, -100])
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels),
                           ignore_index=-100)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = -np.log(p[[0, 2], [1, 2]]).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-4)


def test_attention_oracle(rng):
    b, s, h, d = 2, 8, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    out = F.scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), is_causal=True)
    # numpy oracle
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = np.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_gqa_attention(rng):
    b, s, hq, hkv, d = 1, 4, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert out.shape == (b, s, hq, d)


def test_rope_rotation_properties(rng):
    b, s, h, d = 1, 6, 2, 8
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    cos, sin = F.rope_cos_sin(s, d)
    q2, k2 = F.apply_rotary_pos_emb(jnp.asarray(q), jnp.asarray(k), cos, sin)
    # norm-preserving
    np.testing.assert_allclose(np.linalg.norm(np.asarray(q2), axis=-1),
                               np.linalg.norm(q, axis=-1), rtol=1e-4)
    # position 0 unchanged
    np.testing.assert_allclose(np.asarray(q2)[:, 0], q[:, 0], rtol=1e-5, atol=1e-6)
    # relative property: dot(q_m, k_n) depends only on m-n (spot check)
    def dot(qr, kr, m, n):
        return float(np.sum(np.asarray(qr)[0, m, 0] * np.asarray(kr)[0, n, 0]))
    # construct q/k constant across positions
    qc = np.tile(q[:, :1], (1, s, 1, 1))
    kc = np.tile(k[:, :1], (1, s, 1, 1))
    q3, k3 = F.apply_rotary_pos_emb(jnp.asarray(qc), jnp.asarray(kc), cos, sin)
    assert abs(dot(q3, k3, 3, 1) - dot(q3, k3, 4, 2)) < 1e-3


def test_conv2d_oracle(rng):
    x = rng.standard_normal((1, 3, 8, 8)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)
    out = F.conv2d(jnp.asarray(x), jnp.asarray(w), padding=1)
    assert out.shape == (1, 4, 8, 8)
    # compare center pixel against direct computation
    patch = x[0, :, 2:5, 2:5]
    expect = (patch[None] * w).sum(axis=(1, 2, 3))
    np.testing.assert_allclose(np.asarray(out)[0, :, 3, 3], expect, rtol=1e-3,
                               atol=1e-4)


def test_group_norm_oracle(rng):
    x = rng.standard_normal((2, 4, 3, 3)).astype(np.float32)
    out = F.group_norm(jnp.asarray(x), num_groups=2)
    g = x.reshape(2, 2, 2, 3, 3)
    mu = g.mean(axis=(2, 3, 4), keepdims=True)
    var = g.var(axis=(2, 3, 4), keepdims=True)
    expect = ((g - mu) / np.sqrt(var + 1e-5)).reshape(2, 4, 3, 3)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-4)


def test_dropout_scaling():
    x = jnp.ones((1000,))
    out = F.dropout(x, p=0.5, training=True)
    kept = np.asarray(out) > 0
    assert 0.35 < kept.mean() < 0.65
    np.testing.assert_allclose(np.asarray(out)[kept], 2.0)
    np.testing.assert_allclose(np.asarray(F.dropout(x, 0.5, training=False)), 1.0)


def test_swiglu():
    x = jnp.asarray([[1.0, -1.0]])
    y = jnp.asarray([[2.0, 2.0]])
    out = F.swiglu(x, y)
    expect = (np.asarray(x) / (1 + np.exp(-np.asarray(x)))) * np.asarray(y)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_interpolate_and_pool(rng):
    x = jnp.asarray(rng.standard_normal((1, 2, 4, 4)).astype(np.float32))
    up = F.interpolate(x, scale_factor=2, mode="nearest")
    assert up.shape == (1, 2, 8, 8)
    avg = F.avg_pool2d(x, 2)
    np.testing.assert_allclose(np.asarray(avg)[0, 0, 0, 0],
                               np.asarray(x)[0, 0, :2, :2].mean(), rtol=1e-5)
    mx = F.max_pool2d(x, 2)
    np.testing.assert_allclose(np.asarray(mx)[0, 0, 0, 0],
                               np.asarray(x)[0, 0, :2, :2].max(), rtol=1e-5)


def test_grad_through_functional(rng):
    """Gradient check vs finite differences (reference check_grad pattern)."""
    x = jnp.asarray(rng.standard_normal((3, 5)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((5,)).astype(np.float32))

    def f(w):
        return F.rms_norm(x, w).sum()

    g = jax.grad(f)(w)
    eps = 1e-3
    for i in range(5):
        wp = w.at[i].add(eps)
        wm = w.at[i].add(-eps)
        fd = (float(f(wp)) - float(f(wm))) / (2 * eps)
        assert abs(fd - float(g[i])) < 5e-2, (i, fd, float(g[i]))


class TestLinalgTailRound2:
    def test_lu_unpack_matches_torch(self):
        import torch
        from paddle_tpu.ops import linalg
        a = np.random.default_rng(0).normal(size=(5, 5)).astype(np.float32)
        lu, piv = linalg.lu(jnp.asarray(a))
        P, L, U = linalg.lu_unpack(lu, piv)
        np.testing.assert_allclose(np.asarray(P @ L @ U), a, atol=1e-5)
        tp, tl, tu = torch.lu_unpack(*torch.linalg.lu_factor(
            torch.tensor(a)))
        np.testing.assert_allclose(np.asarray(P), tp.numpy(), atol=1e-6)
        np.testing.assert_allclose(np.asarray(L), tl.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(U), tu.numpy(), atol=1e-5)

    def test_svdvals_and_norms(self):
        import torch
        from paddle_tpu.ops import linalg
        a = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.svdvals(jnp.asarray(a))),
            torch.linalg.svdvals(torch.tensor(a)).numpy(), rtol=1e-4)
        np.testing.assert_allclose(
            float(linalg.vector_norm(jnp.asarray(a))),
            float(np.linalg.norm(a.ravel())), rtol=1e-5)
        np.testing.assert_allclose(
            float(linalg.matrix_norm(jnp.asarray(a))),
            float(np.linalg.norm(a, "fro")), rtol=1e-5)

    def test_svd_lowrank_reconstructs(self):
        """Exact-rank-3 matrix, q=3: the randomized range finder must
        recover it (full-rank inputs lose the weakest directions to the
        float32 power iteration — the method's documented regime is
        effectively-low-rank data)."""
        from paddle_tpu.ops import linalg
        r = np.random.default_rng(2)
        b = (r.normal(size=(8, 3)) @ r.normal(size=(3, 5))).astype(
            np.float32)
        u, s, v = linalg.svd_lowrank(jnp.asarray(b), q=3, niter=2)
        np.testing.assert_allclose(np.asarray(u @ jnp.diag(s) @ v.T), b,
                                   atol=1e-4)
        assert s.shape == (3,) and u.shape == (8, 3) and v.shape == (5, 3)

    def test_ormqr_full_q_vs_torch(self):
        import torch
        from paddle_tpu.ops import linalg
        A = torch.tensor(np.random.default_rng(3).normal(
            size=(6, 3)).astype(np.float32))
        h, tau = torch.geqrf(A)
        C = torch.tensor(np.random.default_rng(4).normal(
            size=(6, 2)).astype(np.float32))
        D = torch.tensor(np.random.default_rng(5).normal(
            size=(2, 6)).astype(np.float32))   # right-multiply operand
        for left, trans in ((True, False), (True, True),
                            (False, False), (False, True)):
            c = C if left else D
            ref = torch.ormqr(h, tau, c, left=left,
                              transpose=trans).numpy()
            ours = np.asarray(linalg.ormqr(
                jnp.asarray(h.numpy()), jnp.asarray(tau.numpy()),
                jnp.asarray(c.numpy()), left=left, transpose=trans))
            np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_householder_product(self):
        import torch
        from paddle_tpu.ops import linalg
        A = torch.tensor(np.random.default_rng(5).normal(
            size=(5, 3)).astype(np.float32))
        h, tau = torch.geqrf(A)
        ref = torch.linalg.householder_product(h, tau).numpy()
        ours = np.asarray(linalg.householder_product(
            jnp.asarray(h.numpy()), jnp.asarray(tau.numpy())))
        np.testing.assert_allclose(ours, ref, atol=1e-4)
