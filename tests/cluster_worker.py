"""Training worker for the multi-process cluster tests
(tests/test_multiprocess_cluster.py).

Launched by ``paddle_tpu.launch`` (or directly, for the single-process
reference run).  Each OS process provisions PDTPU_TEST_DEVICES virtual CPU
devices, joins the jax.distributed cluster through
``paddle_tpu.distributed.init_parallel_env`` (the exact wiring a real
multi-host TPU pod uses — reference: paddle.distributed.init_parallel_env),
and trains a tiny MLP with dp over ALL global devices.  The global batch is
derived from the step index alone, so loss trajectories are comparable
across cluster topologies.

Env protocol (PDTPU_TEST_*):
  DEVICES   virtual CPU devices per process (default 4)
  STEPS     total train steps (default 10)
  OUT       path: rank 0 appends one JSON line per run/generation
  CKPT_DIR  if set, save a sharded checkpoint every step + resume-on-start
  KILL_RANK / KILL_STEP  simulate node death: this process SIGKILLs itself
            after completing (and checkpointing) step KILL_STEP — only on a
            fresh (non-resumed) run, so the relaunch survives
  STEP_SLEEP  seconds to sleep after each step (gives an external killer a
            window to land mid-training; default 0)
  TOPO      "dp" (default), "zero": (dp, sharding=2) mesh with ZeRO-2
            partitioned optimizer state — a shrink/grow across THIS
            topology forces reshard-on-load of partitioned moments;
            "zero_scale": sharding=devices//2, so growing the world SPLITS
            each moment shard across more devices (not just remaps it)
  DIM       feature width (default 16; "zero" runs need >= 64 so the
            weights clear the ZERO_MIN_SIZE sharding floor)
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + os.environ.get("PDTPU_TEST_DEVICES", "4"))
sys.path.insert(0, os.environ["PDTPU_REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import ckpt, distributed as dist, nn  # noqa: E402
from paddle_tpu.jit import TrainStep  # noqa: E402
from paddle_tpu.optimizer import AdamW  # noqa: E402

GLOBAL_BATCH = 32
DIM = int(os.environ.get("PDTPU_TEST_DIM", "16"))
HIDDEN = max(32, 2 * DIM)


def make_serving_engine(args):
    """Engine factory for the cluster serving worker CLI
    (``python -m paddle_tpu.serving.worker --factory
    tests/cluster_worker.py:make_serving_engine``): a tiny llama built
    under ``--seed`` so every process — and the in-test reference —
    holds identical weights."""
    import paddle_tpu as pt
    from paddle_tpu import serving

    from paddle_tpu.models.llama import llama

    pt.seed(args.seed)
    model = llama("tiny")
    return serving.Engine(model, max_batch=2, max_seq_len=64,
                          page_size=8, prefill_chunk=8, role=args.role)


def global_batch(step: int):
    g = np.random.default_rng(1000 + step)
    return {"x": g.standard_normal((GLOBAL_BATCH, DIM)).astype(np.float32),
            "y": g.standard_normal((GLOBAL_BATCH, DIM)).astype(np.float32)}


def main():
    dist.init_parallel_env()
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    topo = os.environ.get("PDTPU_TEST_TOPO", "dp")
    pt.seed(0)
    model = nn.Sequential(nn.Linear(DIM, HIDDEN), nn.ReLU(),
                          nn.Linear(HIDDEN, DIM))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    loss_fn = lambda m, b: ((m(b["x"]) - b["y"]) ** 2).mean()  # noqa: E731
    if topo in ("zero", "zero_scale"):
        # (dp, sharding) hybrid: optimizer moments ZeRO-partitioned over
        # the sharding axis — world changes across THIS mesh exercise
        # reshard-on-load of partitioned state, not just dp data resharding.
        # "zero": sharding=2 fixed (the shrink e2e).  "zero_scale":
        # sharding=devices//2, so a 1->2 grow SPLITS each previously-held
        # moment shard across twice as many devices (VERDICT r4 #5b).
        shard_deg = 2 if topo == "zero" else max(2, jax.device_count() // 2)
        devs = np.array(jax.devices()).reshape(-1, shard_deg)
        mesh = Mesh(devs, ("dp", "sharding"))
        step = TrainStep(model, loss_fn, opt, mesh=mesh, zero_stage=2)
        batch_sharding = NamedSharding(mesh, P(("dp", "sharding")))
    else:
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        step = TrainStep(model, loss_fn, opt, mesh=mesh)
        batch_sharding = NamedSharding(mesh, P("dp"))
    state = step.init_state(seed=0)

    total = int(os.environ.get("PDTPU_TEST_STEPS", "10"))
    ckpt_dir = os.environ.get("PDTPU_TEST_CKPT_DIR") or None
    kill_rank = int(os.environ.get("PDTPU_TEST_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("PDTPU_TEST_KILL_STEP", "-1"))

    start, resumed_from = 0, None
    if ckpt_dir:
        latest = ckpt.latest_checkpoint(ckpt_dir)
        if latest:
            # reshard-on-load: the checkpoint may have been written by a
            # different (larger) cluster; each device reads its own window
            state = ckpt.load_state_dict(latest, template=state)
            start, resumed_from = int(state["step"]), latest

    losses = {}
    for s in range(start, total):
        full = global_batch(s)
        batch = {k: jax.make_array_from_callback(
                     v.shape, batch_sharding, lambda idx, v=v: v[idx])
                 for k, v in full.items()}
        state, met = step(state, batch)
        losses[s] = float(met["loss"])
        if ckpt_dir:
            ckpt.save_state_dict(state, os.path.join(ckpt_dir, f"step_{s + 1}"))
        sleep = float(os.environ.get("PDTPU_TEST_STEP_SLEEP", "0"))
        if sleep:
            import time
            time.sleep(sleep)
        if (resumed_from is None and s + 1 == kill_step
                and jax.process_index() == kill_rank):
            import signal
            os.kill(os.getpid(), signal.SIGKILL)

    if jax.process_index() == 0:
        record = {"losses": losses, "world": jax.process_count(),
                  "devices": jax.device_count(), "start": start,
                  "resumed_from": resumed_from}
        with open(os.environ["PDTPU_TEST_OUT"], "a") as f:
            f.write(json.dumps(record) + "\n")
    print("worker-done", flush=True)


if __name__ == "__main__":
    main()
