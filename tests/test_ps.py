"""Parameter-server tests (SURVEY §2.5 'Parameter server' row).

Mirrors the reference test pattern (test/ps/, test_dist_base.py): tables
exercised directly, then an end-to-end sparse CTR model where the dense
half runs as a jitted device step and embedding rows ride pull/push."""

import numpy as np
import pytest

pytestmark = pytest.mark.cluster  # OS-process e2e: excluded by -m "not cluster"

import jax
import jax.numpy as jnp

from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.ps import (
    DenseTable, DistributedEmbedding, GeoWorkerTable, PaddleCloudRoleMaker,
    PsClient, PsRuntime, PsService, SparseAccessor, SparseTable, TableConfig,
)


def _uniform_init(rng, shape):
    return rng.uniform(-0.1, 0.1, shape)


class TestTables:
    def test_dense_sgd(self):
        t = DenseTable("w", (3, 2), SparseAccessor("sgd", lr=0.5))
        g = np.ones((3, 2), np.float32)
        t.push(g)
        np.testing.assert_allclose(t.pull(), -0.5 * g)

    def test_sparse_lazy_init_deterministic(self):
        a = SparseTable("e", 4, initializer=_uniform_init, seed=7)
        b = SparseTable("e", 4, initializer=_uniform_init, seed=7)
        ka = a.pull([5, 9])
        np.testing.assert_array_equal(ka, b.pull([5, 9]))
        assert len(a) == 2

    def test_sparse_adagrad_adam_slots(self):
        for rule in ("adagrad", "adam"):
            t = SparseTable("e", 3, SparseAccessor(rule, lr=0.1))
            keys = np.array([1, 2])
            g = np.ones((2, 3), np.float32)
            before = t.pull(keys).copy()
            for _ in range(3):
                t.push(keys, g)
            after = t.pull(keys)
            assert (after < before).all()  # moved against the gradient

    def test_state_dict_roundtrip(self):
        t = SparseTable("e", 2, initializer=_uniform_init)
        t.pull([3, 1, 4])
        s = t.state_dict()
        t2 = SparseTable("e", 2)
        t2.load_state_dict(s)
        np.testing.assert_array_equal(t.pull([1, 3, 4]), t2.pull([1, 3, 4]))

    def test_state_dict_preserves_optimizer_slots(self):
        """Resume must keep adam moments/steps — identical trajectories."""
        def make():
            t = SparseTable("e", 3, SparseAccessor("adam", lr=0.1),
                            initializer=_uniform_init, seed=1)
            t.push([1, 2], np.ones((2, 3), np.float32))
            return t
        a, b = make(), make()
        restored = SparseTable("e", 3, SparseAccessor("adam", lr=0.1))
        restored.rows = {99: np.ones(3, np.float32)}  # stale content
        restored.slots = {99: np.zeros((2, 3), np.float32)}
        restored.load_state_dict(a.state_dict())
        assert 99 not in restored.rows and 99 not in restored.slots
        g = np.full((2, 3), 0.5, np.float32)
        b.push([1, 2], g)
        restored.push([1, 2], g)
        np.testing.assert_allclose(restored.pull([1, 2]), b.pull([1, 2]),
                                   atol=1e-7)


class TestClientSharding:
    def test_pull_push_spans_servers(self):
        cfg = [TableConfig("emb", "sparse", dim=2, rule="sgd", lr=1.0,
                           initializer=_uniform_init)]
        servers = [PsService(cfg, i) for i in range(3)]
        c = PsClient(servers)
        keys = np.arange(10)
        rows = c.pull_sparse("emb", keys)
        assert rows.shape == (10, 2)
        # rows landed on owner servers only (key % 3)
        for s in range(3):
            assert set(servers[s].tables["emb"].rows) == \
                {int(k) for k in keys if k % 3 == s}
        c.push_sparse("emb", keys, np.ones((10, 2), np.float32))
        np.testing.assert_allclose(c.pull_sparse("emb", keys), rows - 1.0,
                                   atol=1e-6)

    def test_dense_home_and_empty_pull(self):
        cfg = [TableConfig("w", "dense", shape=(2, 2), rule="sgd", lr=1.0)]
        c = PsClient([PsService(cfg, i) for i in range(2)])
        c.push_dense("w", np.ones((2, 2)))
        np.testing.assert_allclose(c.pull_dense("w"), -np.ones((2, 2)))
        cfg2 = [TableConfig("e", "sparse", dim=5)]
        c2 = PsClient([PsService(cfg2, 0)])
        assert c2.pull_sparse("e", np.zeros(0)).shape == (0, 5)


class TestGeoAsync:
    def test_deltas_merge_upstream(self):
        cfg = [TableConfig("e", "sparse", dim=2, rule="sgd", lr=0.5)]
        server_client = PsClient([PsService(cfg, 0)])
        w = GeoWorkerTable(server_client, "e", 2,
                           SparseAccessor("sgd", lr=0.5), geo_step=2)
        keys = np.array([1, 2])
        g = np.ones((2, 2), np.float32)
        w.pull(keys)
        w.push(keys, g)                      # local only (1 < geo_step)
        srv_rows = server_client.pull_sparse("e", keys)
        np.testing.assert_allclose(srv_rows, 0.0)
        w.push(keys, g)                      # hits geo_step → delta shipped
        srv_rows = server_client.pull_sparse("e", keys)
        np.testing.assert_allclose(srv_rows, -1.0)  # two lr=0.5 sgd steps

    def test_two_workers_converge(self):
        """Two geo workers on disjoint-ish keys both pull the merged view."""
        cfg = [TableConfig("e", "sparse", dim=1, rule="sgd", lr=0.1)]
        server = PsClient([PsService(cfg, 0)])
        w1 = GeoWorkerTable(server, "e", 1, SparseAccessor("sgd", .1), geo_step=1)
        w2 = GeoWorkerTable(server, "e", 1, SparseAccessor("sgd", .1), geo_step=1)
        k = np.array([7])
        for _ in range(5):
            w1.pull(k); w1.push(k, np.ones((1, 1)))
            w2.pull(k); w2.push(k, np.ones((1, 1)))
        merged = server.pull_sparse("e", k)[0, 0]
        assert merged == pytest.approx(-1.0, abs=1e-5)  # 10 × lr .1
        # workers absorb each other's merged contributions on pull
        assert w1.pull(k)[0, 0] == pytest.approx(merged, abs=1e-5)
        assert w2.pull(k)[0, 0] == pytest.approx(merged, abs=1e-5)

    def test_pull_preserves_pending_local_delta(self):
        """Unsent local progress must survive a sync pull."""
        cfg = [TableConfig("e", "sparse", dim=1, rule="sgd", lr=1.0)]
        server = PsClient([PsService(cfg, 0)])
        w = GeoWorkerTable(server, "e", 1, SparseAccessor("sgd", 1.0),
                           geo_step=100)  # never auto-ships
        k = np.array([3])
        w.pull(k)
        w.push(k, np.ones((1, 1)))          # local: -1, server: 0
        # another worker moves the server by -5
        server.push_sparse_delta("e", k, np.full((1, 1), -5.0))
        got = w.pull(k)[0, 0]
        assert got == pytest.approx(-6.0)   # server -5 + pending -1


class TestFleetPsFlow:
    def test_role_maker_env(self):
        env = {"PADDLE_TRAINING_ROLE": "PSERVER",
               "PADDLE_PSERVERS_IP_PORT_LIST": "127.0.0.1:1,127.0.0.1:2",
               "POD_IP": "127.0.0.1", "PADDLE_PORT": "2",
               "PADDLE_TRAINERS_NUM": "3"}
        r = PaddleCloudRoleMaker(env=env)
        assert r.is_server() and r.server_id == 1 and r.server_num() == 2
        r2 = PaddleCloudRoleMaker(env={"PADDLE_TRAINER_ID": "2",
                                       "PADDLE_TRAINERS_NUM": "3"})
        assert r2.is_worker() and r2.worker_index() == 2

    def test_fleet_init_ps_mode(self):
        fleet._reset()
        try:
            rt = fleet.init(PaddleCloudRoleMaker(env={}), is_collective=False)
            assert isinstance(rt, PsRuntime)
            assert fleet.is_worker() and not fleet.is_server()
            fleet.set_ps_tables([TableConfig("e", "sparse", dim=2)])
            assert rt.configs[0].name == "e"
        finally:
            fleet._reset()


class TestEndToEndCTR:
    def test_sparse_lr_converges_with_device_dense_step(self):
        """The TPU PS pattern: pull rows host-side, jitted dense step on
        device returns row grads, push back. A tiny CTR logistic
        regression must fit a deterministic rule."""
        dim = 4
        cfg = [TableConfig("emb", "sparse", dim=dim, rule="adagrad", lr=0.5,
                           initializer=_uniform_init, seed=3)]
        runtime = PsRuntime.local(cfg, num_servers=2)
        emb = DistributedEmbedding(runtime, "emb", dim)

        w = jnp.zeros((dim,), jnp.float32)  # dense head, trained on device

        @jax.jit
        def step(w, rows, inverse, labels):
            def loss_fn(w, rows):
                feats = rows[inverse].sum(1)           # [B, dim] bag-of-ids
                logits = feats @ w
                p = jax.nn.sigmoid(logits)
                eps = 1e-6
                return -jnp.mean(labels * jnp.log(p + eps)
                                 + (1 - labels) * jnp.log(1 - p + eps))
            loss, (dw, drows) = jax.value_and_grad(loss_fn, argnums=(0, 1))(w, rows)
            return loss, w - 0.5 * dw, drows

        rng = np.random.default_rng(0)
        score = np.where(np.arange(20) < 10, 1.0, -1.0)  # additive ground truth
        losses = []
        for it in range(60):
            ids = rng.integers(0, 20, size=(16, 3))
            labels = jnp.asarray((score[ids].sum(1) > 0).astype(np.float32))
            rows, inverse = emb.pull(ids)
            loss, w, drows = step(w, jnp.asarray(rows), jnp.asarray(inverse),
                                  labels)
            emb.push(np.asarray(drows))
            losses.append(float(loss))
        assert losses[-1] < 0.45 < losses[0] + 0.3
        assert np.mean(losses[-10:]) < np.mean(losses[:10])

    def test_duplicate_ids_grads_summed(self):
        cfg = [TableConfig("emb", "sparse", dim=2, rule="sgd", lr=1.0)]
        emb = DistributedEmbedding(PsRuntime.local(cfg), "emb", 2)
        ids = np.array([[5, 5, 3]])
        rows, inverse = emb.pull(ids)
        assert rows.shape[0] == 2  # unique ids only
        # d(loss)/d(rows) where loss = sum(rows[inverse]) → grad 2 for id 5
        d_rows = np.zeros_like(rows)
        np.add.at(d_rows, inverse.ravel(), 1.0)
        emb.push(d_rows)
        out = emb.client.pull_sparse("emb", np.array([5, 3]))
        np.testing.assert_allclose(out[0], -2.0)
        np.testing.assert_allclose(out[1], -1.0)


class TestRpcTransport:
    def test_client_over_rpc_loopback(self):
        """Wire transport: service installed in-process, client calls it
        through the rpc layer (world_size=1 loopback)."""
        from paddle_tpu.distributed import rpc
        from paddle_tpu.distributed.ps.service import _install_service
        from paddle_tpu.launch.store import free_port

        cfg = [TableConfig("e", "sparse", dim=3, rule="sgd", lr=1.0)]
        _install_service(PsService(cfg, 0))
        rpc.init_rpc("ps0", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{free_port()}")
        try:
            c = PsClient(["ps0"])
            keys = np.array([1, 2, 3])
            rows = c.pull_sparse("e", keys)
            np.testing.assert_allclose(rows, 0.0)
            c.push_sparse("e", keys, np.full((3, 3), 2.0, np.float32))
            np.testing.assert_allclose(c.pull_sparse("e", keys), -2.0)
        finally:
            rpc.shutdown()
            _install_service(None)


class TestPsTwoProcesses:
    def test_server_trainer_flow(self, tmp_path):
        """Full reference PS flow across two real processes: PSERVER runs
        until TRAINER 0's stop_worker releases it (SURVEY §2.5/§3.5)."""
        import os
        import subprocess
        import sys
        import textwrap

        from paddle_tpu.launch.store import free_port
        port = free_port()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "ps_job.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            sys.path.insert(0, {repo!r})
            import numpy as np
            from paddle_tpu.distributed import fleet
            from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker,
                                                   TableConfig)

            role = PaddleCloudRoleMaker()
            rt = fleet.init(role, is_collective=False)
            fleet.set_ps_tables(
                [TableConfig("emb", "sparse", dim=2, rule="sgd", lr=1.0)],
                master_endpoint="127.0.0.1:{port}")
            if fleet.is_server():
                fleet.init_server()
                fleet.run_server()          # must return after trainer stop
                print("server exited cleanly")
            else:
                fleet.init_worker()
                keys = np.array([1, 2, 9])
                rows = rt.client.pull_sparse("emb", keys)
                assert rows.shape == (3, 2) and (rows == 0).all()
                rt.client.push_sparse("emb", keys,
                                      np.ones((3, 2), np.float32))
                out = rt.client.pull_sparse("emb", keys)
                assert (out == -1.0).all(), out
                print("trainer ok")
                fleet.stop_worker()
        """))
        base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PADDLE_PSERVERS_IP_PORT_LIST": "127.0.0.1:9000",
                "PADDLE_TRAINERS_NUM": "1"}
        srv = subprocess.Popen(
            [sys.executable, str(script)],
            env={**base, "PADDLE_TRAINING_ROLE": "PSERVER",
                 "POD_IP": "127.0.0.1", "PADDLE_PORT": "9000"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        trn = subprocess.Popen(
            [sys.executable, str(script)],
            env={**base, "PADDLE_TRAINING_ROLE": "TRAINER",
                 "PADDLE_TRAINER_ID": "0"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        t_out, _ = trn.communicate(timeout=120)
        assert trn.returncode == 0, t_out
        assert "trainer ok" in t_out
        s_out, _ = srv.communicate(timeout=60)   # must NOT hang
        assert srv.returncode == 0, s_out
        assert "server exited cleanly" in s_out


class TestSnapshotRestore:
    """Server-side fault tolerance (round-4 M99, VERDICT r3 missing #5):
    table snapshots + restore-on-restart."""

    def test_snapshot_roundtrip_local(self, tmp_path):
        cfgs = [TableConfig("emb", "sparse", dim=4, rule="adam", lr=0.1),
                TableConfig("w", "dense", shape=(3, 2), rule="sgd", lr=1.0)]
        svc = PsService(cfgs, snapshot_dir=str(tmp_path), snapshot_every=2)
        keys = np.array([5, 9, 1])
        svc.push_sparse("emb", keys, np.ones((3, 4), np.float32))
        svc.push_dense("w", np.full((3, 2), 0.5, np.float32))  # 2nd push → snap
        want_rows = svc.pull_sparse("emb", keys)
        want_w = svc.pull_dense("w")
        # a FRESH service with the same dir restores everything,
        # including adam slots (continued training must match)
        svc2 = PsService(cfgs, snapshot_dir=str(tmp_path))
        np.testing.assert_array_equal(svc2.pull_sparse("emb", keys),
                                      want_rows)
        np.testing.assert_array_equal(svc2.pull_dense("w"), want_w)
        # one more identical push on both must produce identical state
        # (adam moments survived the roundtrip)
        g = np.full((3, 4), 0.25, np.float32)
        svc.push_sparse("emb", keys, g)
        svc2.push_sparse("emb", keys, g)
        np.testing.assert_allclose(svc2.pull_sparse("emb", keys),
                                   svc.pull_sparse("emb", keys), rtol=1e-6)

    def test_no_snapshot_dir_never_writes(self, tmp_path):
        svc = PsService([TableConfig("emb", "sparse", dim=2)])
        svc.push_sparse("emb", np.array([1]), np.ones((1, 2), np.float32))
        import pytest as _pytest
        with _pytest.raises(ValueError):
            svc.save_snapshot()

    def test_kill_server_restore_across_processes(self, tmp_path):
        """SIGKILL the table server mid-job; a relaunched server with the
        same snapshot dir serves the snapshotted rows and training
        continues (reference: PS server fault tolerance, SURVEY §5.3)."""
        import os
        import signal
        import subprocess
        import sys
        import textwrap

        from paddle_tpu.launch.store import free_port
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        snap = str(tmp_path / "snap")
        script = tmp_path / "ps_phase.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys, time
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            sys.path.insert(0, {repo!r})
            import numpy as np
            from paddle_tpu.distributed import fleet
            from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker,
                                                   TableConfig)

            phase = os.environ["PS_PHASE"]
            role = PaddleCloudRoleMaker()
            rt = fleet.init(role, is_collective=False)
            fleet.set_ps_tables(
                [TableConfig("emb", "sparse", dim=2, rule="sgd", lr=1.0)],
                master_endpoint=os.environ["PS_MASTER"])
            rt.snapshot_dir = {snap!r}
            rt.snapshot_every = 1
            if fleet.is_server():
                fleet.init_server()
                fleet.run_server()
                print("server exited cleanly")
            else:
                fleet.init_worker()
                keys = np.array([1, 2, 9])
                if phase == "1":
                    rt.client.push_sparse("emb", keys,
                                          np.ones((3, 2), np.float32))
                    out = rt.client.pull_sparse("emb", keys)
                    assert (out == -1.0).all(), out
                    print("phase1 ok")
                    # no stop_worker: the server gets SIGKILLed instead
                    from paddle_tpu.distributed import rpc
                    rpc.shutdown(graceful=False)
                else:
                    out = rt.client.pull_sparse("emb", keys)
                    # the snapshotted -1 rows survived the kill
                    assert (out == -1.0).all(), out
                    rt.client.push_sparse("emb", keys,
                                          np.ones((3, 2), np.float32))
                    out = rt.client.pull_sparse("emb", keys)
                    assert (out == -2.0).all(), out
                    print("phase2 ok")
                    fleet.stop_worker()
        """))

        def run_phase(phase, expect, kill_server):
            port = free_port()
            base = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PADDLE_PSERVERS_IP_PORT_LIST": "127.0.0.1:9000",
                    "PADDLE_TRAINERS_NUM": "1", "PS_PHASE": phase,
                    "PS_MASTER": f"127.0.0.1:{port}"}
            srv = subprocess.Popen(
                [sys.executable, str(script)],
                env={**base, "PADDLE_TRAINING_ROLE": "PSERVER",
                     "POD_IP": "127.0.0.1", "PADDLE_PORT": "9000"},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            trn = subprocess.Popen(
                [sys.executable, str(script)],
                env={**base, "PADDLE_TRAINING_ROLE": "TRAINER",
                     "PADDLE_TRAINER_ID": "0"},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            t_out, _ = trn.communicate(timeout=120)
            assert trn.returncode == 0, t_out
            assert expect in t_out
            if kill_server:
                srv.send_signal(signal.SIGKILL)   # hard server death
            srv.wait(timeout=60)

        run_phase("1", "phase1 ok", kill_server=True)
        run_phase("2", "phase2 ok", kill_server=False)
