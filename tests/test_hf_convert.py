"""HF checkpoint import + torch-oracle logits parity.

The strongest architecture test in the suite: load a randomly initialized
transformers LlamaForCausalLM into our llama and require token-level
logits agreement (proves rope/attention/norm/mlp wiring matches the
de-facto implementation, not just our own expectations)."""

import numpy as np
import pytest

import jax.numpy as jnp

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import paddle_tpu as pt
from paddle_tpu.models.hf import from_hf, load_hf_state_dict
from paddle_tpu.models.llama import LlamaConfig, llama


def _tiny_pair(tie=False, gqa=False):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2 if gqa else 4,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=tie, attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ours = llama(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2 if gqa else 4,
        max_position_embeddings=64, tie_word_embeddings=tie)).eval()
    return hf, ours


class TestHfConvert:
    @pytest.mark.parametrize("gqa", [False, True])
    def test_logits_parity(self, gqa):
        hf, ours = _tiny_pair(gqa=gqa)
        from_hf(ours, hf)
        ids = np.random.default_rng(0).integers(0, 128, size=(2, 16))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(ours(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)

    def test_transpose_rules(self):
        sd = {"model.layers.0.self_attn.q_proj.weight": np.zeros((8, 4)),
              "model.embed_tokens.weight": np.zeros((10, 4)),
              "model.norm.weight": np.zeros((4,)),
              "model.layers.0.self_attn.rotary_emb.inv_freq": np.zeros(2)}
        out = load_hf_state_dict(sd)
        assert out["model.layers.0.self_attn.q_proj.weight"].shape == (4, 8)
        assert out["model.embed_tokens.weight"].shape == (10, 4)
        assert "model.layers.0.self_attn.rotary_emb.inv_freq" not in out

    def test_mismatch_raises(self):
        hf, ours = _tiny_pair()
        state = hf.state_dict()
        state.pop("model.norm.weight")
        with pytest.raises(ValueError, match="missing"):
            from_hf(ours, state)


class TestHfMixtral:
    def test_logits_parity(self):
        from paddle_tpu.models.mixtral import MixtralConfig, mixtral
        hf_cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            num_local_experts=4, num_experts_per_tok=2,
            rms_norm_eps=1e-5, rope_theta=10000.0,
            tie_word_embeddings=False)
        torch.manual_seed(0)
        hf = transformers.MixtralForCausalLM(hf_cfg).eval()
        ours = mixtral(MixtralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            num_experts=4, top_k=2)).eval()
        from_hf(ours, hf)
        ids = np.random.default_rng(1).integers(0, 128, size=(2, 16))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(ours(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-3)


class TestGenerationParity:
    def test_greedy_matches_hf(self):
        """Whole KV-cache decode path vs transformers greedy generate."""
        hf, ours = _tiny_pair()
        from_hf(ours, hf)
        ids = np.random.default_rng(2).integers(5, 120, size=(1, 8))
        with torch.no_grad():
            ref = hf.generate(torch.tensor(ids), max_new_tokens=12,
                              do_sample=False).numpy()
        got = np.asarray(ours.generate(jnp.asarray(ids), max_new_tokens=12,
                                       temperature=0.0))
        np.testing.assert_array_equal(got[:, ids.shape[1]:],
                                      ref[:, ids.shape[1]:])


class TestHfGpt2:
    def test_logits_parity(self):
        from paddle_tpu.models.gpt import GPTConfig, gpt
        hf_cfg = transformers.GPT2Config(
            vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
            n_inner=None, activation_function="gelu_new",
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
            layer_norm_epsilon=1e-5)
        torch.manual_seed(0)
        hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
        ours = gpt(GPTConfig(
            vocab_size=128, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            tie_word_embeddings=True)).eval()
        from_hf(ours, hf)
        ids = np.random.default_rng(3).integers(0, 128, size=(2, 12))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(ours(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-3)


class TestHfBert:
    def test_logits_parity(self):
        from paddle_tpu.models.bert import bert
        hf_cfg = transformers.BertConfig(
            vocab_size=128, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            layer_norm_eps=1e-12)
        torch.manual_seed(0)
        hf = transformers.BertModel(hf_cfg).eval()
        ours = bert("tiny").eval()
        from_hf(ours, hf)
        rng = np.random.default_rng(4)
        ids = rng.integers(0, 128, size=(2, 16))
        mask = np.ones((2, 16), np.int64)
        mask[1, 10:] = 0  # padding on one row
        with torch.no_grad():
            out = hf(torch.tensor(ids), attention_mask=torch.tensor(mask))
        seq, pooled = ours(jnp.asarray(ids),
                           attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(
            np.asarray(seq)[:, :10], out.last_hidden_state.numpy()[:, :10],
            atol=5e-4, rtol=5e-3)
        np.testing.assert_allclose(np.asarray(pooled),
                                   out.pooler_output.numpy(),
                                   atol=5e-4, rtol=5e-3)


class TestHfT5:
    def test_logits_parity(self):
        from paddle_tpu.models.t5 import T5Config, t5
        hf_cfg = transformers.T5Config(
            vocab_size=128, d_model=64, d_kv=16, d_ff=128, num_layers=2,
            num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=32,
            relative_attention_max_distance=128,
            dropout_rate=0.0, layer_norm_epsilon=1e-6,
            feed_forward_proj="relu", tie_word_embeddings=True,
            decoder_start_token_id=0, pad_token_id=0, eos_token_id=1)
        torch.manual_seed(0)
        hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()
        ours = t5("tiny").eval()
        from_hf(ours, hf)
        rng = np.random.default_rng(5)
        enc_ids = rng.integers(2, 128, size=(2, 12))
        dec_ids = rng.integers(2, 128, size=(2, 7))
        mask = np.ones((2, 12), np.int64)
        mask[1, 9:] = 0
        with torch.no_grad():
            ref = hf(input_ids=torch.tensor(enc_ids),
                     attention_mask=torch.tensor(mask),
                     decoder_input_ids=torch.tensor(dec_ids)).logits.numpy()
        got = np.asarray(ours(jnp.asarray(enc_ids), jnp.asarray(dec_ids),
                              attention_mask=jnp.asarray(mask)))
        np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-3)


class TestHfErnie:
    def test_logits_parity_with_task_ids(self):
        from paddle_tpu.models.ernie import ernie
        hf_cfg = transformers.ErnieConfig(
            vocab_size=128, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64, type_vocab_size=2,
            task_type_vocab_size=3, use_task_id=True,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            layer_norm_eps=1e-12)
        torch.manual_seed(0)
        hf = transformers.ErnieModel(hf_cfg).eval()
        ours = ernie("tiny").eval()
        from_hf(ours, hf)
        rng = np.random.default_rng(5)
        ids = rng.integers(0, 128, size=(2, 16))
        task = rng.integers(0, 3, size=(2, 16))
        mask = np.ones((2, 16), np.int64)
        mask[0, 12:] = 0
        with torch.no_grad():
            out = hf(torch.tensor(ids), attention_mask=torch.tensor(mask),
                     task_type_ids=torch.tensor(task))
        seq, pooled = ours(jnp.asarray(ids),
                           attention_mask=jnp.asarray(mask),
                           task_type_ids=jnp.asarray(task))
        np.testing.assert_allclose(
            np.asarray(seq)[:, :12], out.last_hidden_state.numpy()[:, :12],
            atol=5e-4, rtol=5e-3)
        np.testing.assert_allclose(np.asarray(pooled),
                                   out.pooler_output.numpy(),
                                   atol=5e-4, rtol=5e-3)

    def test_task_embedding_changes_output(self):
        """The ERNIE-specific path must actually contribute."""
        import paddle_tpu as pt
        from paddle_tpu.models.ernie import ernie
        pt.seed(0)
        m = ernie("tiny").eval()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 128, size=(1, 8)))
        a, _ = m(ids, task_type_ids=jnp.zeros((1, 8), jnp.int32))
        b, _ = m(ids, task_type_ids=jnp.ones((1, 8), jnp.int32))
        assert float(jnp.abs(a - b).max()) > 1e-4
