"""Round-3 distribution tail — scipy/torch oracle tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as pt
from paddle_tpu import distribution as D


@pytest.fixture(autouse=True)
def _seed():
    pt.seed(7)


class TestLogProbs:
    def test_gamma(self):
        d = D.Gamma(2.5, 1.5)
        x = np.asarray([0.3, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(d.log_prob(jnp.asarray(x))),
            st.gamma.logpdf(x, 2.5, scale=1 / 1.5), rtol=1e-5)
        np.testing.assert_allclose(float(d.mean), 2.5 / 1.5, rtol=1e-6)
        np.testing.assert_allclose(
            float(d.entropy()), st.gamma.entropy(2.5, scale=1 / 1.5),
            rtol=1e-5)

    def test_chi2(self):
        d = D.Chi2(4.0)
        x = np.asarray([0.5, 2.0, 7.0], np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(jnp.asarray(x))),
                                   st.chi2.logpdf(x, 4.0), rtol=1e-5)

    def test_poisson(self):
        d = D.Poisson(3.0)
        k = np.asarray([0.0, 2.0, 5.0], np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(jnp.asarray(k))),
                                   st.poisson.logpmf(k, 3.0), rtol=1e-5)

    def test_cauchy(self):
        d = D.Cauchy(1.0, 2.0)
        x = np.asarray([-3.0, 0.0, 5.0], np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(jnp.asarray(x))),
                                   st.cauchy.logpdf(x, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(d.cdf(jnp.asarray(x))),
                                   st.cauchy.cdf(x, 1.0, 2.0), rtol=1e-5)

    def test_student_t(self):
        d = D.StudentT(5.0, 0.5, 2.0)
        x = np.asarray([-1.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(d.log_prob(jnp.asarray(x))),
            st.t.logpdf(x, 5.0, loc=0.5, scale=2.0), rtol=1e-5)

    def test_binomial(self):
        d = D.Binomial(10, 0.3)
        k = np.asarray([0.0, 3.0, 10.0], np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(jnp.asarray(k))),
                                   st.binom.logpmf(k, 10, 0.3), rtol=1e-4)

    def test_multinomial(self):
        p = np.asarray([0.2, 0.3, 0.5], np.float32)
        d = D.Multinomial(6, p)
        x = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            float(d.log_prob(jnp.asarray(x))),
            st.multinomial.logpmf(x, 6, p), rtol=1e-5)
        s = d.sample((50,))
        assert s.shape == (50, 3)
        np.testing.assert_allclose(np.asarray(s.sum(-1)), 6.0)

    def test_mvn(self):
        mu = np.asarray([0.5, -1.0], np.float32)
        cov = np.asarray([[2.0, 0.3], [0.3, 1.0]], np.float32)
        d = D.MultivariateNormal(mu, covariance_matrix=cov)
        x = np.asarray([[0.0, 0.0], [1.0, -2.0]], np.float32)
        np.testing.assert_allclose(
            np.asarray(d.log_prob(jnp.asarray(x))),
            st.multivariate_normal.logpdf(x, mu, cov), rtol=1e-5)
        np.testing.assert_allclose(
            float(d.entropy()), st.multivariate_normal.entropy(mu, cov),
            rtol=1e-5)
        s = np.asarray(d.sample((4000,)))
        np.testing.assert_allclose(s.mean(0), mu, atol=0.15)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.2)

    def test_continuous_bernoulli(self):
        import torch
        d = D.ContinuousBernoulli(0.3)
        td = torch.distributions.ContinuousBernoulli(0.3)
        x = np.asarray([0.1, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(
            np.asarray(d.log_prob(jnp.asarray(x))),
            td.log_prob(torch.tensor(x)).numpy(), rtol=1e-4)
        np.testing.assert_allclose(float(d.mean), float(td.mean), rtol=1e-4)


class TestTransforms:
    def test_transformed_lognormal(self):
        td = D.TransformedDistribution(D.Normal(0.2, 0.8),
                                       [D.ExpTransform()])
        ref = D.LogNormal(0.2, 0.8)
        x = jnp.asarray([0.5, 1.0, 2.5])
        np.testing.assert_allclose(np.asarray(td.log_prob(x)),
                                   np.asarray(ref.log_prob(x)), rtol=1e-5)

    def test_affine_chain_roundtrip(self):
        chain = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                                  D.TanhTransform()])
        x = jnp.asarray([-0.5, 0.0, 0.7])
        y = chain.forward(x)
        np.testing.assert_allclose(np.asarray(chain.inverse(y)),
                                   np.asarray(x), rtol=1e-5)

    def test_sigmoid_power_ldj(self):
        import torch
        x = np.asarray([-1.0, 0.3, 2.0], np.float32)
        ours = np.asarray(D.SigmoidTransform()
                          .forward_log_det_jacobian(jnp.asarray(x)))
        ref = (torch.distributions.transforms.SigmoidTransform()
               .log_abs_det_jacobian(torch.tensor(x),
                                     torch.sigmoid(torch.tensor(x))))
        np.testing.assert_allclose(ours, ref.numpy(), rtol=1e-5)
        p = np.asarray([0.5, 1.5, 3.0], np.float32)
        ours = np.asarray(D.PowerTransform(2.0)
                          .forward_log_det_jacobian(jnp.asarray(p)))
        np.testing.assert_allclose(ours, np.log(2.0 * p), rtol=1e-5)


class TestSampling:
    def test_moments(self):
        n = 8000
        g = D.Gamma(3.0, 2.0).sample((n,))
        np.testing.assert_allclose(float(g.mean()), 1.5, atol=0.1)
        p = D.Poisson(4.0).sample((n,))
        np.testing.assert_allclose(float(p.mean()), 4.0, atol=0.15)
        t = D.StudentT(10.0, 1.0, 0.5).sample((n,))
        np.testing.assert_allclose(float(t.mean()), 1.0, atol=0.1)
        b = D.Binomial(12, 0.25).sample((n,))
        np.testing.assert_allclose(float(b.mean()), 3.0, atol=0.15)
