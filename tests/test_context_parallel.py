"""Context-parallel (sep axis) equivalence tests: ring attention and
Ulysses all-to-all attention must match serial attention numerics —
forward AND gradients — on the 8-device CPU mesh (the reference pattern:
parallel == serial, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import cp, fleet
from paddle_tpu.nn import functional as F


@pytest.fixture(autouse=True)
def reset_fleet():
    yield
    fleet._reset()


def _init_sep(sep=4, dp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"sep_degree": sep, "dp_degree": dp}
    return fleet.init(is_collective=True, strategy=strategy)


def _qkv(rng, b=2, s=64, h=4, hkv=4, d=16):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    return q, k, v


def _serial(q, k, v, causal):
    return F.scaled_dot_product_attention(q, k, v, is_causal=causal)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_forward_matches_serial(rng, causal, impl):
    _init_sep(sep=4)
    q, k, v = _qkv(rng)
    want = _serial(q, k, v, causal)
    got = jax.jit(lambda *a: cp.context_parallel_attention(
        *a, causal=causal, impl=impl))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_grads_match_serial(rng, causal, impl):
    _init_sep(sep=4)
    q, k, v = _qkv(rng, b=1, s=32, h=4, hkv=4, d=8)

    def loss_parallel(q, k, v):
        o = cp.context_parallel_attention(q, k, v, causal=causal, impl=impl)
        return jnp.sum(o * o)

    def loss_serial(q, k, v):
        o = _serial(q, k, v, causal)
        return jnp.sum(o * o)

    gp = jax.jit(jax.grad(loss_parallel, argnums=(0, 1, 2)))(q, k, v)
    gs = jax.grad(loss_serial, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_gqa(rng, impl):
    # 4 q heads, 2 kv heads, sep=2: exercises the grouped-query paths
    _init_sep(sep=2)
    q, k, v = _qkv(rng, b=1, s=32, h=4, hkv=2, d=8)
    want = _serial(q, k, v, True)
    got = jax.jit(lambda *a: cp.context_parallel_attention(
        *a, causal=True, impl=impl))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_gqa_repeat_branch(rng):
    # hkv=2 does not divide sep=4: exercises the kv repeat-interleave path
    _init_sep(sep=4)
    q, k, v = _qkv(rng, b=1, s=32, h=4, hkv=2, d=8)
    want = _serial(q, k, v, True)
    got = jax.jit(lambda *a: cp.ulysses_attention(*a, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_cp_composes_with_dp(rng):
    _init_sep(sep=4, dp=2)
    q, k, v = _qkv(rng, b=4, s=32, h=4, hkv=4, d=8)
    want = _serial(q, k, v, True)
    got = jax.jit(lambda *a: cp.ring_attention(*a, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_cp_no_mesh_falls_back(rng):
    q, k, v = _qkv(rng, b=1, s=16, h=2, hkv=2, d=8)
    want = _serial(q, k, v, True)
    got = cp.ring_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_llama_with_context_parallel_matches_serial(impl):
    """End-to-end: tiny llama loss + grads identical with and without cp."""
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import causal_lm_loss, llama
    from paddle_tpu import optimizer

    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, 256, (2, 33)), jnp.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(jnp.int32)}

    pt.seed(0)
    serial = llama("tiny")
    loss_s = causal_lm_loss(serial, batch)

    fleet._reset()
    _init_sep(sep=2, dp=1)
    pt.seed(0)
    par = llama("tiny", context_parallel=impl)
    loss_p = jax.jit(lambda b: causal_lm_loss(par, b))(batch)
    np.testing.assert_allclose(float(loss_p), float(loss_s),
                               atol=3e-5, rtol=3e-5)


class TestFlashRing:
    """Pallas-chunk ring (VERDICT r2 #6 stage B): per-chunk compute via
    flash_attention_with_lse + base-2 lse merge, exercised through the
    Pallas interpreter on the CPU mesh."""

    @pytest.fixture(autouse=True)
    def interpret_mode(self, monkeypatch):
        import functools as ft
        from jax.experimental import pallas as pl
        real = pl.pallas_call
        monkeypatch.setattr(pl, "pallas_call",
                            ft.partial(real, interpret=True))

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("hkv", [4, 2])
    def test_flash_ring_matches_serial_fwd_bwd(self, rng, causal, hkv):
        from jax.sharding import Mesh
        q = jnp.asarray(rng.standard_normal((2, 256, 4, 32))
                        .astype("float32"))
        k = jnp.asarray(rng.standard_normal((2, 256, hkv, 32))
                        .astype("float32"))
        v = jnp.asarray(rng.standard_normal((2, 256, hkv, 32))
                        .astype("float32"))
        mesh = Mesh(np.array(jax.devices()[:4]), ("sep",))
        scale = 1.0 / np.sqrt(32)
        ref = cp._serial_attention(q, k, v, causal, scale)
        out = cp.ring_attention(q, k, v, causal=causal, mesh=mesh,
                                use_flash=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-2)

        gf = jax.grad(lambda *a: (cp.ring_attention(
            *a, causal=causal, mesh=mesh, use_flash=True) ** 2).sum(),
            (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: (cp._serial_attention(
            *a, causal, scale) ** 2).sum(), (0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            scale_b = max(float(jnp.max(jnp.abs(b))), 1.0)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-2 * scale_b)


def test_flash_lse_cotangent_matches_reference(rng, monkeypatch):
    """flash_attention_with_lse: the lse output is differentiable (the
    cotangent folds into delta' = delta - dlse*log2e)."""
    import functools as ft
    from jax.experimental import pallas as pl
    import paddle_tpu.ops.pallas.flash_attention as fa
    monkeypatch.setattr(pl, "pallas_call",
                        ft.partial(pl.pallas_call, interpret=True))
    q = jnp.asarray(rng.standard_normal((1, 64, 2, 16)).astype("float32"))

    def ref(q):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, q) / np.sqrt(16)
        mask = jnp.tril(jnp.ones((64, 64), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse2 = jax.scipy.special.logsumexp(s, -1) * np.log2(np.e)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), q)
        return (out ** 2).sum() + (jnp.sin(lse2) * 3.0).sum()

    def ours(q):
        out, lse = fa.flash_attention_with_lse(q, q, q, causal=True,
                                               block_q=32, block_k=32)
        return (out ** 2).sum() + (jnp.sin(lse) * 3.0).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(ours)(q)),
                               np.asarray(jax.grad(ref)(q)),
                               atol=1e-4)
