"""ZeRO group-sharded tests (SURVEY.md §4: parallel == serial numerics).

Reference pattern: test/collective/fleet/hybrid_parallel_sharding_model.py
— train under each sharding stage and compare losses to the unsharded run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet, group_sharded_parallel
from paddle_tpu.distributed.sharding import (DygraphShardingOptimizer,
                                             GroupShardedOptimizerStage2,
                                             zero_stage_of)
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.llama import causal_lm_loss, llama


@pytest.fixture(autouse=True)
def _fleet_reset():
    yield
    fleet._reset()


def _run(level=None, steps=4):
    fleet._reset()
    pt.seed(0)
    mesh = None
    if level is not None:
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"sharding_degree": 4, "dp_degree": 2}
        hcg = fleet.init(strategy=s)
        mesh = hcg.mesh
    model = llama("tiny")
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    scaler = None
    if level is not None:
        model, opt, scaler = group_sharded_parallel(model, opt, level)
    step = TrainStep(model, causal_lm_loss, opt, mesh=mesh)
    state = step.init_state(seed=0)
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 32))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32),
             "labels": jnp.asarray(np.roll(ids, -1, 1), jnp.int32)}
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses, step, state


def test_all_stages_match_serial():
    serial, _, _ = _run(None)
    for level in ("os", "os_g", "p_g_os"):
        sharded, step, _ = _run(level)
        np.testing.assert_allclose(serial, sharded, rtol=2e-4,
                                   err_msg=f"level={level}")


def test_stage_recorded_on_optimizer():
    pt.seed(0)
    model = llama("tiny", num_hidden_layers=1)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"sharding_degree": 8}
    fleet.init(strategy=s)
    for level, want in (("os", 1), ("os_g", 2), ("p_g_os", 3)):
        m2, o2, _ = group_sharded_parallel(model, opt, level)
        assert zero_stage_of(o2, None) == want
        # wrapper still exposes the inner optimizer API
        assert o2.apply is not None and o2.init is not None
    with pytest.raises(ValueError):
        group_sharded_parallel(model, opt, "bogus")


def test_stage3_param_storage_is_sharded():
    """p_g_os must actually shard parameter storage over the zero axes."""
    _, step, state = _run("p_g_os", steps=1)
    assert step.zero_stage == 3
    mesh = step.mesh
    big = {k: v for k, v in state["params"].items() if v.ndim >= 2}
    sharded = 0
    for k, v in big.items():
        spec = step.param_specs()[k]
        if any(e in ("sharding", "dp") or
               (isinstance(e, tuple) and
                any(a in ("sharding", "dp") for a in e))
               for e in spec if e is not None):
            sharded += 1
    assert sharded >= len(big) // 2, (
        f"only {sharded}/{len(big)} big params zero-sharded")


def test_stage2_grads_use_zero_sharded_specs():
    """ZeRO-2's signature: large grads carry the zero-axis sharding (XLA
    then reduce-scatters them; the CPU partitioner lowers that as
    all-reduce + slice, so assert on the specs, not HLO strings)."""
    _, step, state = _run("os_g", steps=1)
    assert step.zero_stage == 2
    pspecs = step.param_specs()
    gspecs = step.grad_specs(state["params"], pspecs)
    zeroed = [k for k, spec in gspecs.items()
              if any(e in ("sharding", "dp") for e in spec if e is not None)
              and spec != pspecs[k]]
    big = [k for k, v in state["params"].items() if v.size >= 2048]
    assert len(zeroed) >= len(big) // 2, (
        f"only {len(zeroed)} grads zero-sharded of {len(big)} big params")
    # stage 1 must NOT shard grads beyond the param spec
    _, step1, state1 = _run("os", steps=1)
    g1 = step1.grad_specs(state1["params"], step1.param_specs())
    assert g1 == step1.param_specs()
