"""Rows-sparse (SelectedRows-equivalent) gradients — VERDICT r2 #7.

Reference: paddle/fluid/framework/selected_rows.h + phi selected_rows
kernels (sparse SGD, Adam lazy_mode).  Contract: sparse-grad training
matches dense numerics on touched rows; untouched rows keep stale Adam
moments (lazy) or are untouched entirely (SGD)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.optimizer import SGD, Adam
from paddle_tpu.sparse import RowsGrad, embedding_rows_grad

VOCAB, DIM = 20, 4


def _rows_case(rng, n=6, dup=True):
    ids = rng.integers(0, VOCAB, size=(n,))
    if dup:
        ids[1] = ids[0]  # guaranteed duplicate
    vals = rng.standard_normal((n, DIM)).astype("float32")
    return jnp.asarray(ids), jnp.asarray(vals)


class TestRowsGrad:
    def test_to_dense_scatter_adds_duplicates(self, rng):
        ids, vals = _rows_case(rng)
        rg = RowsGrad(ids.astype(jnp.int32), vals, (VOCAB, DIM))
        dense = np.zeros((VOCAB, DIM), np.float32)
        for i, r in enumerate(np.asarray(ids)):
            dense[r] += np.asarray(vals)[i]
        np.testing.assert_allclose(np.asarray(rg.to_dense()), dense,
                                   rtol=1e-6)

    def test_coalesce_merges_and_preserves_dense(self, rng):
        ids, vals = _rows_case(rng)
        rg = RowsGrad(ids.astype(jnp.int32), vals, (VOCAB, DIM))
        cg = rg.coalesce()
        np.testing.assert_allclose(np.asarray(cg.to_dense()),
                                   np.asarray(rg.to_dense()), rtol=1e-6)
        # every in-range row unique after coalesce
        rows = np.asarray(cg.rows)
        in_range = rows[rows < VOCAB]
        assert len(in_range) == len(set(in_range.tolist()))

    def test_padding_idx_dropped(self, rng):
        ids = jnp.asarray([3, 7, 3, 0])
        dout = jnp.ones((4, DIM), jnp.float32)
        rg = embedding_rows_grad(ids, dout, VOCAB, padding_idx=7)
        dense = np.asarray(rg.to_dense())
        assert dense[7].sum() == 0.0
        assert dense[3].sum() == 2 * DIM

    def test_works_under_jit(self, rng):
        ids, vals = _rows_case(rng)

        @jax.jit
        def f(ids, vals):
            return RowsGrad(ids.astype(jnp.int32), vals,
                            (VOCAB, DIM)).coalesce().to_dense()

        np.testing.assert_allclose(
            np.asarray(f(ids, vals)),
            np.asarray(RowsGrad(ids.astype(jnp.int32), vals,
                                (VOCAB, DIM)).to_dense()), rtol=1e-6)


def _embedding_model_and_batch(rng):
    pt.seed(0)
    emb = nn.Embedding(VOCAB, DIM, sparse=True)
    ids = jnp.asarray(rng.integers(0, VOCAB, size=(8, 3)))
    target = jnp.asarray(rng.standard_normal((8, 3, DIM)).astype("float32"))
    return emb, ids, target


class TestSparseTrainingMatchesDense:
    def _grads(self, emb, ids, target):
        def loss_fn(w):
            out = jax.nn.embedding_lookup if False else w[ids]
            return ((out - target) ** 2).mean()

        loss, dense_g = jax.value_and_grad(loss_fn)(emb.weight)

        def out_grad(w):
            out = w[ids]
            return ((out - target) ** 2).mean()

        dout = jax.grad(lambda o: ((o - target) ** 2).mean())(emb.weight[ids])
        rg = emb.rows_grad(ids, dout)
        return dense_g, rg

    def test_sgd_rows_equals_dense(self, rng):
        emb, ids, target = _embedding_model_and_batch(rng)
        dense_g, rg = self._grads(emb, ids, target)
        opt_d = SGD(learning_rate=0.1)
        opt_s = SGD(learning_rate=0.1)
        params = {"weight": emb.weight}
        sd = opt_d.init(params)
        ss = opt_s.init(params)
        p_dense, _ = opt_d.apply({"weight": dense_g}, sd, params)
        p_rows, _ = opt_s.apply({"weight": rg}, ss, params)
        np.testing.assert_allclose(np.asarray(p_rows["weight"]),
                                   np.asarray(p_dense["weight"]), atol=1e-6)

    def test_adam_lazy_touched_rows_match_dense_untouched_stale(self, rng):
        emb, ids, target = _embedding_model_and_batch(rng)
        dense_g, rg = self._grads(emb, ids, target)
        params = {"weight": emb.weight}
        opt_d = Adam(learning_rate=0.01)
        opt_l = Adam(learning_rate=0.01, lazy_mode=True)
        sd = opt_d.init(params)
        sl = opt_l.init(params)
        p_dense, sd = opt_d.apply({"weight": dense_g}, sd, params)
        p_lazy, sl = opt_l.apply({"weight": rg}, sl, params)
        touched = sorted(set(np.asarray(ids).ravel().tolist()))
        untouched = [r for r in range(VOCAB) if r not in touched]
        # touched rows: identical to the dense update (dense grad there is
        # exactly the scatter-added rows grad, and moments started at 0)
        np.testing.assert_allclose(
            np.asarray(p_lazy["weight"])[touched],
            np.asarray(p_dense["weight"])[touched], atol=1e-5)
        # untouched rows: lazy leaves them (and their moments) alone
        np.testing.assert_allclose(
            np.asarray(p_lazy["weight"])[untouched],
            np.asarray(params["weight"])[untouched], atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(sl["moment1"]["weight"])[untouched], 0.0, atol=0)

    def test_multi_step_sgd_training_matches(self, rng):
        """Full loop: N sparse-SGD steps == N dense-SGD steps."""
        emb, _, _ = _embedding_model_and_batch(rng)
        w_dense = emb.weight
        w_rows = emb.weight
        opt = SGD(learning_rate=0.05)
        s_d = opt.init({"w": w_dense})
        s_r = opt.init({"w": w_rows})
        for i in range(5):
            ids = jnp.asarray(rng.integers(0, VOCAB, size=(6, 2)))
            tgt = jnp.asarray(
                rng.standard_normal((6, 2, DIM)).astype("float32"))

            def loss(w):
                return ((w[ids] - tgt) ** 2).mean()

            gd = jax.grad(loss)(w_dense)
            dout = jax.grad(lambda o: ((o - tgt) ** 2).mean())(w_rows[ids])
            rg = embedding_rows_grad(ids, dout, VOCAB)
            pd, s_d = opt.apply({"w": gd}, s_d, {"w": w_dense})
            pr, s_r = opt.apply({"w": rg}, s_r, {"w": w_rows})
            w_dense, w_rows = pd["w"], pr["w"]
        np.testing.assert_allclose(np.asarray(w_rows), np.asarray(w_dense),
                                   atol=1e-5)

    def test_default_optimizer_densifies(self, rng):
        """Optimizers without a sparse rule fall back to densify (same
        numerics as dense)."""
        from paddle_tpu.optimizer import Momentum
        emb, ids, target = _embedding_model_and_batch(rng)
        dense_g, rg = self._grads(emb, ids, target)
        params = {"weight": emb.weight}
        opt1, opt2 = (Momentum(learning_rate=0.1, momentum=0.9)
                      for _ in range(2))
        p_d, _ = opt1.apply({"weight": dense_g}, opt1.init(params), params)
        p_r, _ = opt2.apply({"weight": rg}, opt2.init(params), params)
        np.testing.assert_allclose(np.asarray(p_r["weight"]),
                                   np.asarray(p_d["weight"]), atol=1e-6)
