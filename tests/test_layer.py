"""Layer system tests: construction, traversal, state_dict, functional bridge.

Modeled on the reference's Layer tests (test/legacy_test/test_imperative_*).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn.layer import functional_call, raw_params


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)
        self.drop = nn.Dropout(0.5)

    def forward(self, x):
        return self.fc2(self.drop(pt.nn.functional.relu(self.fc1(x))))


def test_parameter_registration():
    m = MLP()
    names = [n for n, _ in m.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    assert m.fc1.weight.shape == (4, 8)
    assert m.fc1.bias.shape == (8,)


def test_state_dict_roundtrip():
    m = MLP()
    sd = m.state_dict()
    assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    m2 = MLP()
    m2.set_state_dict(sd)
    for (k1, v1), (k2, v2) in zip(m.named_parameters(), m2.named_parameters()):
        np.testing.assert_allclose(v1, v2)


def test_forward_eager():
    m = MLP().eval()
    x = jnp.ones((3, 4))
    y = m(x)
    assert y.shape == (3, 2)


def test_functional_call_pure():
    m = MLP().eval()
    params = raw_params(m)
    x = jnp.ones((3, 4))
    y1 = m(x)
    zeroed = {k: jnp.zeros_like(v) for k, v in params.items()}
    y0 = functional_call(m, zeroed, x)
    np.testing.assert_allclose(np.asarray(y0), 0.0)
    # original params restored after the call
    y2 = m(x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))


def test_functional_call_jit_grad():
    m = MLP().eval()
    params = raw_params(m)
    x = jnp.ones((3, 4))

    @jax.jit
    def loss_fn(p):
        return functional_call(m, p, x).sum()

    g = jax.grad(loss_fn)(dict(params))
    assert set(g) == set(params)
    assert g["fc2.bias"].shape == (2,)
    np.testing.assert_allclose(np.asarray(g["fc2.bias"]), 3.0)  # sum over batch


def test_dropout_rng_determinism():
    m = MLP().train()
    params = raw_params(m)
    x = jnp.ones((5, 4))
    key = jax.random.key(7)
    y1 = functional_call(m, params, x, rngs=key, training=True)
    y2 = functional_call(m, params, x, rngs=key, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
    y3 = functional_call(m, params, x, rngs=jax.random.key(8), training=True)
    assert not np.allclose(np.asarray(y1), np.asarray(y3))


def test_train_eval_mode():
    m = MLP()
    assert m.training and m.drop.training
    m.eval()
    assert not m.training and not m.drop.training
    m.train()
    assert m.drop.training


def test_buffers():
    class WithBuf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("count", jnp.zeros((1,)))
            self.fc = nn.Linear(2, 2)

        def forward(self, x):
            return self.fc(x) + self.count

    m = WithBuf()
    sd = m.state_dict()
    assert "count" in sd and "fc.weight" in sd
    params = raw_params(m)
    assert "count" not in params  # buffers are not parameters


def test_sequential_and_layerlist():
    s = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    y = s(jnp.ones((1, 3)))
    assert y.shape == (1, 2)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll[0].named_parameters())) == 2


def test_trainable_mask():
    m = MLP()
    meta = m.param_meta()
    assert all(meta[k].trainable for k in meta)
    m2 = nn.Linear(2, 2, weight_attr=nn.ParamAttr(trainable=False))
    mask = pt.nn.trainable_mask(m2)
    assert mask["weight"] is False and mask["bias"] is True


def test_apply_and_astype():
    m = MLP()
    m.astype("bfloat16")
    assert m.fc1.weight.dtype == jnp.bfloat16
    m.astype("float32")
    assert m.fc1.weight.dtype == jnp.float32


def test_hooks():
    m = nn.Linear(2, 2)
    calls = []
    h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(jnp.ones((1, 2)))
    assert calls == [1]
    h.remove()
    m(jnp.ones((1, 2)))
    assert calls == [1]


class TestNnUtils:
    def test_weight_norm_reparam(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import remove_weight_norm, weight_norm

        pt.seed(0)
        lin = nn.Linear(4, 3, bias_attr=False)
        w0 = np.asarray(lin.weight)
        wrapped = weight_norm(lin, dim=0)
        x = jnp.ones((2, 4))
        out1 = np.asarray(wrapped(x))
        # effective weight equals original at init: g = ||v||
        np.testing.assert_allclose(out1, np.ones((2, 4)) @ w0, atol=1e-5)
        # params are now g and v, not the raw weight
        names = dict(wrapped.named_parameters())
        assert any(k.endswith("weight_g") for k in names)
        assert not any(k.endswith("layer.weight") for k in names)
        # grads flow to both g and v
        from paddle_tpu.nn.layer import functional_call, raw_params
        p = raw_params(wrapped)
        g = jax.grad(lambda p: functional_call(wrapped, p, x).sum())(p)
        assert all(np.abs(np.asarray(v)).sum() > 0 for v in g.values())
        inner = remove_weight_norm(wrapped)
        np.testing.assert_allclose(np.asarray(inner(x)), out1, atol=1e-5)

    def test_spectral_norm_scales_to_unit_sigma(self):
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import spectral_norm

        pt.seed(0)
        lin = nn.Linear(6, 5, bias_attr=False)
        sn = spectral_norm(lin, n_power_iterations=30)
        _ = sn(jnp.ones((1, 6)))  # eager: u converges
        w = np.asarray(lin.weight)
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        out = np.asarray(sn(jnp.ones((1, 6))))
        expect = np.ones((1, 6)) @ (w / sigma)
        np.testing.assert_allclose(out, expect, rtol=1e-3)

    def test_vector_roundtrip(self):
        import jax.numpy as jnp
        from paddle_tpu.nn.utils import (parameters_to_vector,
                                         vector_to_parameters)

        ps = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,))]
        vec = parameters_to_vector(ps)
        assert vec.shape == (10,)
        back = vector_to_parameters(vec, ps)
        for a, b in zip(ps, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_spectral_norm_zero_power_iterations():
    """n_power_iterations=0 must reuse the cached u (reference accepts 0)."""
    from paddle_tpu import nn
    from paddle_tpu.nn.utils import spectral_norm
    lin = nn.Linear(4, 3)
    sn = spectral_norm(lin, n_power_iterations=0)
    out = sn(jnp.ones((2, 4)))
    assert out.shape == (2, 3)
    assert bool(jnp.all(jnp.isfinite(out)))
