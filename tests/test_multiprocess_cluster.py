"""Multi-process cluster end-to-end tests.

The one code path a real multi-host TPU pod depends on that single-process
tests cannot reach: ``paddle_tpu.launch`` → per-process env protocol →
``init_parallel_env`` → ``jax.distributed.initialize`` → cross-process
collectives (gloo on CPU, ICI/DCN on TPU) → joint training.  SURVEY §4
patterns 2-3, §5.3, §5.8.

Three contracts:
- cluster parity: 2 OS processes × 4 virtual CPU devices each train dp=8
  jointly and reproduce the single-process 8-device loss trajectory.
- elastic shrink-resume: kill one node mid-run → the surviving node detects
  the death, relaunches at a smaller world size, resumes from the sharded
  checkpoint via reshard-on-load, and the continued trajectory matches an
  uninterrupted reference run.
- elastic grow-resume: a node joins a HEALTHY below-MAX job mid-run → the
  running cluster sees the join request, advances the shared rendezvous
  round, relaunches at the larger world, and resumes from the latest
  checkpoint with the trajectory again matching the reference run
  (reference: fleet elastic manager relaunches on ANY membership change,
  node-join included — SURVEY §2.7, §5.3).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.cluster  # OS-process e2e: excluded by -m "not cluster"

from paddle_tpu.launch import CollectiveController, parse_args
from paddle_tpu.launch.store import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "cluster_worker.py")


def _read_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _run_single_reference(tmp_path, steps):
    """Uninterrupted single-process 8-device run of the same training."""
    out = str(tmp_path / "single.jsonl")
    env = {**os.environ, "PDTPU_REPO": REPO, "PDTPU_TEST_DEVICES": "8",
           "PDTPU_TEST_STEPS": str(steps), "PDTPU_TEST_OUT": out}
    for k in ("PDTPU_COORDINATOR", "PDTPU_TEST_CKPT_DIR",
              "PDTPU_TEST_KILL_RANK", "PDTPU_TEST_KILL_STEP",
              "PDTPU_TEST_STEP_SLEEP"):
        env.pop(k, None)
    r = subprocess.run([sys.executable, WORKER], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    (rec,) = _read_records(out)
    return rec


class TestClusterParity:
    STEPS = 8

    def test_two_processes_match_single_process(self, tmp_path, monkeypatch):
        out = str(tmp_path / "cluster.jsonl")
        monkeypatch.setenv("PDTPU_REPO", REPO)
        monkeypatch.setenv("PDTPU_TEST_DEVICES", "4")
        monkeypatch.setenv("PDTPU_TEST_STEPS", str(self.STEPS))
        monkeypatch.setenv("PDTPU_TEST_OUT", out)
        monkeypatch.delenv("PDTPU_TEST_CKPT_DIR", raising=False)

        ctx = parse_args(["--nproc_per_node", "2", "--job_id", "mpc1",
                          "--log_dir", str(tmp_path / "log"), WORKER])
        assert CollectiveController(ctx).run() == 0

        (cluster,) = _read_records(out)
        assert cluster["world"] == 2 and cluster["devices"] == 8
        single = _run_single_reference(tmp_path, self.STEPS)
        a = [cluster["losses"][str(i)] for i in range(self.STEPS)]
        b = [single["losses"][str(i)] for i in range(self.STEPS)]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestElasticShrinkResume:
    STEPS = 10
    KILL_AFTER = 5  # kill node 1 once the step_5 checkpoint is complete

    def test_kill_node_shrink_world_resume_from_ckpt(self, tmp_path,
                                                     monkeypatch):
        out = str(tmp_path / "elastic.jsonl")
        ckpt_dir = str(tmp_path / "ckpt")
        port = free_port()
        master = f"127.0.0.1:{port}"

        monkeypatch.setenv("PDTPU_REPO", REPO)
        monkeypatch.setenv("PDTPU_TEST_DEVICES", "4")
        monkeypatch.setenv("PDTPU_TEST_STEPS", str(self.STEPS))
        monkeypatch.setenv("PDTPU_TEST_OUT", out)
        monkeypatch.setenv("PDTPU_TEST_CKPT_DIR", ckpt_dir)
        # node death: node B's worker (global rank 1) SIGKILLs itself right
        # after checkpointing step KILL_AFTER, and node B's controller gives
        # up (--max_restarts 0) — the node is gone, exactly like a host
        # failure mid-job
        monkeypatch.setenv("PDTPU_TEST_KILL_RANK", "1")
        monkeypatch.setenv("PDTPU_TEST_KILL_STEP", str(self.KILL_AFTER))

        env_b = {**os.environ, "PYTHONPATH": REPO}
        node_b = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.launch",
             "--nnodes", "1:2", "--rank", "1", "--master", master,
             "--nproc_per_node", "1", "--elastic_level", "1",
             "--elastic_timeout", "4", "--max_restarts", "0",
             "--job_id", "mpc2",
             "--log_dir", str(tmp_path / "log_b"), WORKER],
            env=env_b, cwd=REPO, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        # node A: the surviving node, driven in the main thread (signal
        # handlers require it); hosts the rendezvous store (rank 0); its
        # worker must NOT kill itself (it is rank 0)
        ctx = parse_args(["--nnodes", "1:2", "--rank", "0",
                          "--master", master, "--nproc_per_node", "1",
                          "--elastic_level", "1", "--elastic_timeout", "4",
                          "--job_id", "mpc2",
                          "--log_dir", str(tmp_path / "log_a"), WORKER])
        try:
            rc = CollectiveController(ctx).run()
        finally:
            try:
                os.killpg(node_b.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            node_b.wait(timeout=30)

        assert rc == 0
        records = _read_records(out)
        # generation 0 died before rank 0 finished → only the resumed
        # (shrunk) generation reports
        final = records[-1]
        assert final["world"] == 1 and final["devices"] == 4
        assert final["resumed_from"] is not None
        # resumed from the kill-point checkpoint (or at worst one step
        # earlier, if the survivor was torn down mid-save)
        assert self.KILL_AFTER - 1 <= final["start"] <= self.KILL_AFTER

        single = _run_single_reference(tmp_path, self.STEPS)
        steps = sorted(int(s) for s in final["losses"])
        assert steps[-1] == self.STEPS - 1
        a = [final["losses"][str(i)] for i in steps]
        b = [single["losses"][str(i)] for i in steps]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestElasticShrinkResumeSharded:
    """Shrink across a SHARDED (dp, sharding=2) ZeRO-2 topology: the
    relaunch must reshard-on-load partitioned optimizer moments (8-device
    (4,2) mesh -> 4-device (2,2) mesh), not just redistribute dp data."""

    STEPS = 10
    KILL_AFTER = 5

    def test_kill_node_shrink_sharded_state(self, tmp_path, monkeypatch):
        out = str(tmp_path / "elastic_sharded.jsonl")
        ckpt_dir = str(tmp_path / "ckpt")
        port = free_port()
        master = f"127.0.0.1:{port}"

        monkeypatch.setenv("PDTPU_REPO", REPO)
        monkeypatch.setenv("PDTPU_TEST_DEVICES", "4")
        monkeypatch.setenv("PDTPU_TEST_STEPS", str(self.STEPS))
        monkeypatch.setenv("PDTPU_TEST_OUT", out)
        monkeypatch.setenv("PDTPU_TEST_CKPT_DIR", ckpt_dir)
        monkeypatch.setenv("PDTPU_TEST_TOPO", "zero")
        monkeypatch.setenv("PDTPU_TEST_DIM", "64")
        monkeypatch.setenv("PDTPU_TEST_KILL_RANK", "1")
        monkeypatch.setenv("PDTPU_TEST_KILL_STEP", str(self.KILL_AFTER))

        env_b = {**os.environ, "PYTHONPATH": REPO}
        node_b = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.launch",
             "--nnodes", "1:2", "--rank", "1", "--master", master,
             "--nproc_per_node", "1", "--elastic_level", "1",
             "--elastic_timeout", "4", "--max_restarts", "0",
             "--job_id", "mpc4",
             "--log_dir", str(tmp_path / "log_b"), WORKER],
            env=env_b, cwd=REPO, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        ctx = parse_args(["--nnodes", "1:2", "--rank", "0",
                          "--master", master, "--nproc_per_node", "1",
                          "--elastic_level", "1", "--elastic_timeout", "4",
                          "--job_id", "mpc4",
                          "--log_dir", str(tmp_path / "log_a"), WORKER])
        try:
            rc = CollectiveController(ctx).run()
        finally:
            try:
                os.killpg(node_b.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            node_b.wait(timeout=30)

        assert rc == 0
        final = _read_records(out)[-1]
        assert final["world"] == 1 and final["devices"] == 4
        assert final["resumed_from"] is not None
        assert self.KILL_AFTER - 1 <= final["start"] <= self.KILL_AFTER

        single = _run_single_reference(tmp_path, self.STEPS)
        steps = sorted(int(s) for s in final["losses"])
        assert steps[-1] == self.STEPS - 1
        a = [final["losses"][str(i)] for i in steps]
        b = [single["losses"][str(i)] for i in steps]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestElasticGrowResume:
    """Scale-UP: node B joins a healthy world-1 job mid-run."""

    def test_node_join_grows_world_resume_from_ckpt(self, tmp_path,
                                                    monkeypatch):
        final, steps_total = _run_grow_e2e(tmp_path, monkeypatch,
                                           job_id="mpc3", out_name="grow")
        # the job finished at the GROWN world, resumed from a checkpoint
        # taken while running alone
        assert final["world"] == 2 and final["devices"] == 8
        assert final["resumed_from"] is not None
        assert 1 <= final["start"] <= steps_total - 1

        single = _run_single_reference(tmp_path, steps_total)
        steps = sorted(int(s) for s in final["losses"])
        assert steps[-1] == steps_total - 1
        a = [final["losses"][str(i)] for i in steps]
        b = [single["losses"][str(i)] for i in steps]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def _run_grow_e2e(tmp_path, monkeypatch, job_id, out_name, steps=12,
                  join_delay=22, elastic_timeout=3, extra_env=None):
    """Shared elastic scale-UP choreography: node A boots alone (gen-0
    settle admits a 1-node quorum), trains with per-step checkpoints, and
    node B's delayed join grows the world mid-run.  join_delay must exceed
    A's settle window (elastic_timeout + 15s) plus a couple of steps; the
    2.5 s/step sleep stretches training so the join lands mid-run."""
    out = str(tmp_path / f"{out_name}.jsonl")
    ckpt_dir = str(tmp_path / "ckpt")
    master = f"127.0.0.1:{free_port()}"

    monkeypatch.setenv("PDTPU_REPO", REPO)
    monkeypatch.setenv("PDTPU_TEST_DEVICES", "4")
    monkeypatch.setenv("PDTPU_TEST_STEPS", str(steps))
    monkeypatch.setenv("PDTPU_TEST_OUT", out)
    monkeypatch.setenv("PDTPU_TEST_CKPT_DIR", ckpt_dir)
    monkeypatch.setenv("PDTPU_TEST_STEP_SLEEP", "2.5")
    monkeypatch.delenv("PDTPU_TEST_KILL_RANK", raising=False)
    monkeypatch.delenv("PDTPU_TEST_KILL_STEP", raising=False)
    for k, v in (extra_env or {}).items():
        monkeypatch.setenv(k, v)

    common = ["--nnodes", "1:2", "--master", master,
              "--nproc_per_node", "1", "--elastic_level", "1",
              "--elastic_timeout", str(elastic_timeout),
              "--max_restarts", "2", "--job_id", job_id]
    env_b = {**os.environ, "PYTHONPATH": REPO}
    cmd_b = " ".join(
        [sys.executable, "-m", "paddle_tpu.launch", "--rank", "1",
         "--log_dir", str(tmp_path / "log_b")] + common + [WORKER])
    node_b = subprocess.Popen(
        ["/bin/sh", "-c", f"sleep {join_delay} && exec {cmd_b}"],
        env=env_b, cwd=REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    ctx = parse_args(["--rank", "0",
                      "--log_dir", str(tmp_path / "log_a")]
                     + common + [WORKER])
    try:
        rc = CollectiveController(ctx).run()
    finally:
        try:
            os.killpg(node_b.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        node_b.wait(timeout=30)

    assert rc == 0
    return _read_records(out)[-1], steps


class TestElasticGrowResumeSharded:
    """Scale-UP into a SHARDED topology (VERDICT r4 #5b): node B joins a
    healthy world-1 ZeRO-2 job; the relaunch lands on sharding=4 (was 2),
    so every previously-held partitioned moment must SPLIT across twice
    as many devices on reshard-on-load — the direction a recovering
    preemptible fleet executes."""

    def test_node_join_grow_splits_sharded_state(self, tmp_path,
                                                 monkeypatch):
        final, steps_total = _run_grow_e2e(
            tmp_path, monkeypatch, job_id="mpc5", out_name="grow_sharded",
            extra_env={"PDTPU_TEST_TOPO": "zero_scale",
                       "PDTPU_TEST_DIM": "64"})
        # finished at the grown world: 8 devices, sharding=4 (split from 2)
        assert final["world"] == 2 and final["devices"] == 8
        assert final["resumed_from"] is not None
        assert 1 <= final["start"] <= steps_total - 1

        # reference inherits TOPO=zero_scale (8 devices -> (2,4) mesh),
        # matching the sharded-shrink test's pattern: ZeRO partitioning
        # must not change numerics at any world size
        single = _run_single_reference(tmp_path, steps_total)
        steps = sorted(int(s) for s in final["losses"])
        assert steps[-1] == steps_total - 1
        a = [final["losses"][str(i)] for i in steps]
        b = [single["losses"][str(i)] for i in steps]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestClusterServing:
    """Cluster serving control plane across real OS processes: per-host
    ``python -m paddle_tpu.serving.worker`` loops over a shared
    TCPStore, an in-test ``ClusterController``, and the full failure
    menu in one fleet lifetime — SIGKILL a decode worker mid-churn
    (lease-expiry evacuation), SIGTERM a prefill worker (PreemptionGuard
    graceful drain), then command-driven drain of the rest — with every
    batch greedy token-identical to a colocated single-engine reference
    and every worker's exit report showing zero compiles after warmup
    and a fully reclaimed KV pool."""

    ROLES = ("prefill", "prefill", "decode", "decode")

    def _env(self):
        cache = os.path.abspath(
            os.path.join(REPO, ".pytest_cache", "xla_cache"))
        env = {**os.environ,
               "PDTPU_REPO": REPO,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
               "JAX_COMPILATION_CACHE_DIR": cache,
               "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
               "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
               "ALLOW_MULTIPLE_LIBTPU_LOAD": "1"}
        env.pop("PDTPU_FAULTS", None)
        return env

    def _spawn(self, endpoint, wid, role, env):
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.worker",
             "--store", endpoint, "--role", role,
             "--factory", WORKER + ":make_serving_engine",
             "--worker-id", wid, "--lease-deadline-s", "6",
             "--status-interval-s", "0.05", "--steps-per-poll", "2",
             "--seed", "0"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)

    @staticmethod
    def _assert_alive(procs, may_exit=()):
        for wid, p in procs.items():
            if wid not in may_exit and p.poll() is not None:
                out, err = p.communicate(timeout=10)
                raise AssertionError(
                    f"{wid} died rc={p.returncode}\n{out}\n{err}")

    def _pump_until(self, ctl, procs, rids, *, timeout_s, may_exit=()):
        import time
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            ctl.pump()
            if all(r in ctl.outputs for r in rids):
                return
            self._assert_alive(procs, may_exit)
            time.sleep(0.01)
        missing = [r for r in rids if r not in ctl.outputs]
        raise AssertionError(f"undelivered after {timeout_s}s: {missing}")

    @staticmethod
    def _report(proc, *, timeout=90):
        out, err = proc.communicate(timeout=timeout)
        assert proc.returncode == 0, f"rc={proc.returncode}\n{out}\n{err}"
        lines = [ln for ln in out.splitlines() if ln.strip()]
        assert lines, f"no report on stdout\n{err}"
        return json.loads(lines[-1])

    def test_fleet_kill_sigterm_drain_token_identity(self, tmp_path):
        import time

        import paddle_tpu as pt
        from paddle_tpu import serving
        from paddle_tpu.launch.store import TCPStore
        from paddle_tpu.models.llama import llama

        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, size=n).astype(np.int32)
                   for n in (5, 17, 9, 26)]
        pt.seed(0)
        ref_eng = serving.Engine(llama("tiny"), max_batch=2,
                                 max_seq_len=64, page_size=8,
                                 prefill_chunk=8).warmup()
        ref_rids = [ref_eng.add_request(p, max_new_tokens=8)
                    for p in prompts]
        ref_outs = ref_eng.run()
        ref = [ref_outs[r] for r in ref_rids]
        ref_rids = [ref_eng.add_request(p, max_new_tokens=24)
                    for p in prompts]
        ref_outs = ref_eng.run()
        ref24 = [ref_outs[r] for r in ref_rids]

        env = self._env()
        store = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        procs = {}
        try:
            for i, role in enumerate(self.ROLES):
                wid = f"w{i}-{role}"
                procs[wid] = self._spawn(store.endpoint, wid, role, env)
            ctl = serving.ClusterController(store, lease_deadline_s=6.0)
            deadline = time.time() + 300
            while True:
                self._assert_alive(procs)
                try:
                    ctl.wait_for_workers(4, timeout_s=2.0)
                    break
                except TimeoutError:
                    if time.time() > deadline:
                        raise

            # phase 1: disagg fleet serves token-identically
            rids = [ctl.submit(p, max_new_tokens=8) for p in prompts]
            self._pump_until(ctl, procs, rids, timeout_s=180)
            assert [ctl.outputs[r]["tokens"] for r in rids] == ref

            # phase 2: SIGKILL a decode worker the moment it owns an
            # uncollected assignment (waves of long decodes keep the
            # tier busy — a fixed batch outruns the poll on this tiny
            # model); lease-expiry evacuation re-delivers every wave
            # token-identically
            victim, rids = None, []
            deadline = time.time() + 120
            while victim is None and time.time() < deadline:
                rids += [ctl.submit(p, max_new_tokens=24)
                         for p in prompts]
                wave_end = time.time() + 5
                while victim is None and time.time() < wave_end:
                    ctl.pump()
                    for r in rids:
                        a = ctl._assigned.get(r)
                        if r not in ctl.outputs and a \
                                and a["wid"].endswith("decode"):
                            victim = a["wid"]
                            break
            assert victim, "no decode worker ever owned an assignment"
            procs[victim].kill()
            self._pump_until(ctl, procs, rids, timeout_s=180,
                             may_exit=(victim,))
            for i, r in enumerate(rids):
                assert ctl.outputs[r]["tokens"] == ref24[i % len(ref24)]
            assert ctl.members()[victim]["state"] == "dead"
            survivor = {"w2-decode": "w3-decode",
                        "w3-decode": "w2-decode"}[victim]

            # phase 3: SIGTERM a prefill worker mid-batch — graceful
            # drain hands off, deregisters, exits 0 with a clean report
            rids = [ctl.submit(p, max_new_tokens=8) for p in prompts]
            for _ in range(5):
                ctl.pump()
                time.sleep(0.01)
            procs["w1-prefill"].send_signal(signal.SIGTERM)
            self._pump_until(ctl, procs, rids, timeout_s=180,
                             may_exit=(victim, "w1-prefill"))
            assert [ctl.outputs[r]["tokens"] for r in rids] == ref
            rep = self._report(procs["w1-prefill"])
            assert rep["free_blocks"] == rep["num_blocks"]
            assert rep["compiles_after_warmup"] == 0
            assert ctl.members()["w1-prefill"]["state"] == "left"

            # phase 4: command-driven drain of the survivors
            for wid in ("w0-prefill", survivor):
                ctl.drain_worker(wid)
            for wid in ("w0-prefill", survivor):
                rep = self._report(procs[wid])
                assert rep["free_blocks"] == rep["num_blocks"]
                assert rep["compiles_after_warmup"] == 0
                assert rep["lease_losses"] == 0
                assert ctl.members()[wid]["state"] == "left"
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            store.close()
