"""Launcher tests: TCPStore protocol, rendezvous, pod lifecycle, CLI
end-to-end on localhost, elastic restart, spawn.

Mirrors the reference pattern (SURVEY §4: multi-node logic tested by
env-faking the rendezvous on localhost)."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

pytestmark = pytest.mark.cluster  # OS-process e2e: excluded by -m "not cluster"

from paddle_tpu.launch import (CollectiveController, Context, TCPStore,
                               parse_args)
from paddle_tpu.launch.elastic import ElasticManager
from paddle_tpu.launch.job import Container
from paddle_tpu.launch.master import Master
from paddle_tpu.launch.store import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestTCPStore:
    def test_set_get_add_delete(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            assert s.get("k") is None
            s.set("k", b"v")
            assert s.get("k") == b"v"
            assert s.add("n", 3) == 3
            assert s.add("n", 2) == 5
            assert s.delete("k") and not s.delete("k")
            assert s.keys("") == ["n"]
        finally:
            s.close()

    def test_per_call_timeout_override(self):
        """set/get take a per-call timeout= (KV-page transfer chunks
        need a longer deadline than heartbeats — serving/disagg.py):
        the override lands on the client socket for exactly that call
        and the store's default deadline is restored afterwards."""
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True,
                     timeout=30.0)
        applied = []

        class _Spy:
            def __init__(self, sock):
                self._sock = sock

            def settimeout(self, v):
                applied.append(v)
                self._sock.settimeout(v)

            def __getattr__(self, name):
                return getattr(self._sock, name)

        s._sock = _Spy(s._sock)
        try:
            s.set("big", b"x" * 4096, timeout=75.0)
            assert applied == [75.0, 30.0]          # applied + restored
            assert s._sock.gettimeout() == 30.0
            del applied[:]
            assert s.get("big", timeout=75.0) == b"x" * 4096
            assert applied == [75.0, 30.0]
            del applied[:]
            # no override → the socket deadline is never touched
            assert s.get("big") == b"x" * 4096
            assert applied == []
        finally:
            s.close()

    def test_wait_and_two_clients(self):
        master = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        client = TCPStore(master.endpoint)
        try:
            def setter():
                time.sleep(0.2)
                client.set("late", b"x")
            t = threading.Thread(target=setter)
            t.start()
            assert master.wait("late", timeout=5) == b"x"
            t.join()
            with pytest.raises(TimeoutError):
                master.wait("never", timeout=0.2)
        finally:
            client.close()
            master.close()

    def test_compare_set(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            assert s.compare_set("c", b"", b"1")        # create-if-absent
            assert not s.compare_set("c", b"0", b"2")   # wrong expect
            assert s.compare_set("c", b"1", b"2")
            assert s.get("c") == b"2"
        finally:
            s.close()

    def test_reconnect_with_backoff_after_socket_death(self):
        """A bounced controller kills every client socket.  With a
        ``retry`` policy configured, add/compare_set/keys/delete
        transparently reconnect-and-retry (serving workers must cost a
        controller restart one retry, not their lease)."""
        from paddle_tpu.resilience.retry import RetryPolicy
        master = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        client = TCPStore(master.endpoint,
                          retry=RetryPolicy(max_attempts=4,
                                            backoff_s=0.001))
        try:
            client.set("n", b"v")
            for op in (lambda: client.add("ctr", 1),
                       lambda: client.compare_set("c", b"", b"1"),
                       lambda: client.keys(""),
                       lambda: client.delete("n")):
                dead = client._sock
                dead.close()        # the restart: next send dies
                op()                # reconnects under the policy
                assert client._sock is not dead
            assert client.get("c") == b"1"
            assert client.add("ctr", 1) == 2
            assert client.get("n") is None      # the delete applied
        finally:
            client.close()
            master.close()

    def test_no_retry_policy_still_surfaces_socket_death(self):
        """Without a policy the store keeps its fail-fast contract —
        the reconnect-with-backoff behaviour is strictly opt-in."""
        master = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        client = TCPStore(master.endpoint)
        try:
            client._sock.close()
            with pytest.raises(OSError):
                client.add("ctr", 1)
        finally:
            client.close()
            master.close()

    def test_compare_set_ghost_write_is_idempotent(self):
        """A CAS whose reply died with its socket may have applied
        server-side; the retried attempt then sees expect-mismatch with
        the key already holding OUR value.  That reads as success —
        lease renewal chains CAS on the previous value, so a ghost
        write must not drop the lease."""
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            # server state after the ghost write: v1 -> v2 applied,
            # reply lost; the client retries the same CAS
            s.set("lease", b"v2")
            assert s.compare_set("lease", b"v1", b"v2")
            # a genuine conflict (someone ELSE's value) still fails
            assert not s.compare_set("lease", b"v1", b"v3")
        finally:
            s.close()

    def test_injected_store_faults_retried_under_policy(self):
        """Chaos plans on ``store.set``/``store.get`` cover the cluster
        write ops (add/delete/cas map to set; keys maps to get) and are
        absorbed by the client retry policy."""
        from paddle_tpu import resilience as rs
        from paddle_tpu.resilience.retry import RetryPolicy
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True,
                     retry=RetryPolicy(max_attempts=4, backoff_s=0.001))
        inj = rs.install_faults(
            "store.set@0x2:ConnectionError;store.get@0:ConnectionError")
        try:
            assert s.add("ctr", 1) == 1
            assert s.keys("") == ["ctr"]
            assert ("store.set", 0) in inj.fired
            assert ("store.get", 0) in inj.fired
        finally:
            rs.clear_faults()
            s.close()

    def test_barrier(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        c = TCPStore(s.endpoint)
        errs = []
        def one(store):
            try:
                store.barrier("b1", 2, timeout=5)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        try:
            ts = [threading.Thread(target=one, args=(x,)) for x in (s, c)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs
        finally:
            c.close()
            s.close()


class TestRendezvous:
    def test_two_node_rank_assignment(self):
        port = free_port()
        results = {}

        def node(rank_hint, is_first):
            ctx = Context(nnodes=2, master=f"127.0.0.1:{port}",
                          rank=-1, job_id="t2n")
            # second node must not host the store
            if not is_first:
                ctx.rank = -1
            m = Master.__new__(Master)
            m.ctx = ctx
            m.generation = 0
            m.store = TCPStore(f"127.0.0.1:{port}", is_master=is_first,
                               timeout=10)
            r, eps = m.rendezvous()
            results[rank_hint] = (r, eps)
            m.store.close()

        t0 = threading.Thread(target=node, args=(0, True))
        t1 = threading.Thread(target=node, args=(1, False))
        t0.start(); time.sleep(0.1); t1.start()
        t0.join(); t1.join()
        ranks = sorted(r for r, _ in results.values())
        assert ranks == [0, 1]
        assert all(len(eps) == 2 for _, eps in results.values())


class TestContainer:
    def test_run_and_log(self, tmp_path):
        log = str(tmp_path / "w.log")
        c = Container(entrypoint=[sys.executable, "-c",
                                  "import os;print(os.environ['X_TEST'])"],
                      env={"X_TEST": "hello"}, log_path=log)
        c.start()
        while c.alive():
            time.sleep(0.02)
        assert c.returncode == 0
        c.terminate()
        assert "hello" in open(log).read()

    def test_terminate_kills_group(self, tmp_path):
        c = Container(entrypoint=[sys.executable, "-c",
                                  "import time;time.sleep(60)"],
                      env={}, log_path=str(tmp_path / "w.log"))
        c.start()
        assert c.alive()
        t0 = time.monotonic()
        c.terminate(grace=0.5)
        assert not c.alive()
        assert time.monotonic() - t0 < 10


def _write_script(tmp_path, body):
    p = tmp_path / "train.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


class TestCLI:
    def test_single_node_two_proc(self, tmp_path):
        script = _write_script(tmp_path, """
            import os
            rank = os.environ["PADDLE_TRAINER_ID"]
            world = os.environ["PADDLE_TRAINERS_NUM"]
            assert os.environ["PDTPU_PROCESS_ID"] == rank
            print(f"rank {rank} of {world} ok")
        """)
        log_dir = str(tmp_path / "log")
        ctx = parse_args(["--nproc_per_node", "2", "--log_dir", log_dir,
                          "--job_id", "cli1", script])
        assert CollectiveController(ctx).run() == 0
        logs = sorted(os.listdir(log_dir))
        assert logs == ["workerlog.0", "workerlog.1"]
        assert "rank 0 of 2 ok" in open(os.path.join(log_dir, "workerlog.0")).read()

    def test_failure_propagates(self, tmp_path):
        script = _write_script(tmp_path, """
            import os, sys
            sys.exit(3 if os.environ["PADDLE_TRAINER_ID"] == "1" else 0)
        """)
        ctx = parse_args(["--nproc_per_node", "2",
                          "--log_dir", str(tmp_path / "log"), script])
        assert CollectiveController(ctx).run() != 0

    def test_elastic_restart_recovers(self, tmp_path):
        # first generation fails, relaunch succeeds (marker file flips it)
        marker = tmp_path / "marker"
        script = _write_script(tmp_path, f"""
            import os, sys
            m = {str(repr(str(marker)))}
            if not os.path.exists(m):
                open(m, "w").close()
                sys.exit(1)
            print("recovered")
        """)
        ctx = parse_args(["--nproc_per_node", "1", "--elastic_level", "1",
                          "--max_restarts", "2",
                          "--log_dir", str(tmp_path / "log"), script])
        assert CollectiveController(ctx).run() == 0
        assert "recovered" in open(tmp_path / "log" / "workerlog.0").read()


class TestElasticManager:
    def test_corrupt_heartbeat_counts_as_dead(self):
        """An unparsable heartbeat payload (torn store write) must read
        as a dead node, not crash the liveness watcher every other
        node's recovery depends on."""
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            em = ElasticManager(s, "ejc", node_rank=0, nnodes=2,
                                timeout=0.3, heartbeat_period=0.1)
            em.start()
            s.set(em._key(1), b"not-a-float")
            time.sleep(0.5)   # past the startup grace period
            assert em.dead_nodes() == [1]
            em.stop()
        finally:
            s.close()

    def test_heartbeat_and_dead_detection(self):
        s = TCPStore(f"127.0.0.1:{free_port()}", is_master=True)
        try:
            em = ElasticManager(s, "ej", node_rank=0, nnodes=2, timeout=0.5,
                                heartbeat_period=0.1)
            em.start()
            # inside the startup grace period an absent peer is NOT dead
            time.sleep(0.2)
            assert em.dead_nodes() == []
            # past the grace period node 1 (never heartbeats) is dead,
            # node 0 (own fresh heartbeat) is alive
            time.sleep(0.6)
            assert em.dead_nodes() == [1]
            em.stop()
        finally:
            s.close()


class TestSpawn:
    def test_spawn_single_inprocess(self):
        out = []
        from paddle_tpu.distributed import spawn
        spawn(lambda rank, x: out.append((rank, x)), args=(7,), nprocs=1)
        assert out == [(0, 7)]

    def test_spawn_multiproc(self, tmp_path):
        # run via subprocess to avoid importing jax state into forks
        script = _write_script(tmp_path, """
            import os
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            import sys
            sys.path.insert(0, os.environ["PDTPU_REPO"])
            from paddle_tpu.distributed.spawn import spawn

            def f(rank, base):
                assert os.environ["PADDLE_TRAINER_ID"] == str(rank)
                sys.exit(0 if rank + base >= 0 else 1)

            if __name__ == "__main__":
                spawn(f, args=(0,), nprocs=2)
                print("spawn-ok")
        """)
        env = {**os.environ, "PDTPU_REPO": REPO, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run([sys.executable, script], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert "spawn-ok" in r.stdout


class TestPreemptionGuard:
    def test_sigterm_sets_flag_and_saves_once(self, tmp_path):
        import signal as sig
        from paddle_tpu.launch import PreemptionGuard

        saves = []
        marker = tmp_path / "ck"

        def save():
            saves.append(1)
            marker.write_text("saved")

        with PreemptionGuard(save_fn=save) as guard:
            assert not guard.preempted
            os.kill(os.getpid(), sig.SIGTERM)   # simulated preemption
            time.sleep(0.05)
            assert guard.preempted
        assert saves == [1] and marker.read_text() == "saved"
        # original handler restored: nothing blows up re-entering
        with PreemptionGuard() as g2:
            assert not g2.preempted

    def test_no_preemption_no_save(self):
        from paddle_tpu.launch import PreemptionGuard
        saves = []
        with PreemptionGuard(save_fn=lambda: saves.append(1)):
            pass
        assert saves == []

    def test_checkpoint_resume_roundtrip(self, tmp_path):
        """Preempt mid-training → save → resume from ckpt → loss continues
        falling (the §5.3 restart-based recovery contract)."""
        import signal as sig
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu import nn
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.launch import PreemptionGuard
        from paddle_tpu.optimizer import AdamW

        pt.seed(0)

        def make_step():
            m = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 8))
            opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
            return TrainStep(m, lambda mm, b: ((mm(b["x"]) - b["y"]) ** 2).mean(), opt)

        batch = {"x": jnp.ones((4, 8)), "y": jnp.zeros((4, 8))}
        path = str(tmp_path / "state")
        step = make_step()
        state = step.init_state()
        with PreemptionGuard(save_fn=lambda: pt.save(state, path)) as guard:
            for i in range(20):
                state, met = step(state, batch)
                if i == 5:
                    os.kill(os.getpid(), sig.SIGTERM)
                if guard.preempted:
                    break
        loss_at_preempt = float(met["loss"])

        # "relaunch": fresh step, load the saved state, keep training
        step2 = make_step()
        state2 = pt.load(path)
        # jax.random keys round-trip as raw key_data — rewrap on load
        import jax
        state2["rng"] = jax.random.wrap_key_data(
            jnp.asarray(jax.random.key_data(state["rng"])))
        for _ in range(10):
            state2, met2 = step2(state2, batch)
        assert float(met2["loss"]) < loss_at_preempt

    def test_raising_save_fn_still_restores_handlers(self):
        """A save_fn that raises on exit must not leave the SIGTERM
        handler installed forever on a dead guard."""
        import signal as sig
        from paddle_tpu.launch import PreemptionGuard

        prev = sig.getsignal(sig.SIGTERM)

        def boom():
            raise RuntimeError("ckpt write failed")

        with pytest.raises(RuntimeError, match="ckpt write failed"):
            with PreemptionGuard(save_fn=boom) as guard:
                os.kill(os.getpid(), sig.SIGTERM)
                time.sleep(0.05)
                assert guard.preempted
        assert sig.getsignal(sig.SIGTERM) is prev

    def test_guard_reusable_across_runs(self, tmp_path):
        import signal as sig
        from paddle_tpu.launch import PreemptionGuard
        saves = []
        guard = PreemptionGuard(save_fn=lambda: saves.append(1))
        for attempt in range(2):
            with guard:
                assert not guard.preempted   # stale flag must be cleared
                os.kill(os.getpid(), sig.SIGTERM)
                time.sleep(0.05)
                assert guard.preempted
        assert saves == [1, 1]               # saved on BOTH preemptions
