"""Weight-only int8/int4 quantization for serving (paddle.nn.quant
parity — reference: python/paddle/nn/quant/quantized_linear.py over the
Cutlass fpA_intB GEMM, SURVEY §2.1 Cutlass row).

Quality gates are LOGIT-ERROR bounds (not token agreement — VERDICT r3
weak #3's fix applied here from the start)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import quant as Q


class TestWeightQuantize:
    def test_int8_roundtrip_error(self, rng):
        w = jnp.asarray(rng.standard_normal((64, 48)).astype(np.float32))
        qw, s = Q.weight_quantize(w, algo="weight_only_int8")
        assert qw.dtype == jnp.int8 and qw.shape == w.shape
        assert s.shape == (48,)
        wd = Q.weight_dequantize(qw, s, algo="weight_only_int8")
        # absmax/127 quantization step bounds the error per column
        step = np.abs(np.asarray(w)).max(0) / 127.0
        assert (np.abs(np.asarray(wd - w)) <= step[None, :] + 1e-6).all()

    def test_int4_pack_roundtrip_exact(self, rng):
        w = jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32))
        qw, s = Q.weight_quantize(w, algo="weight_only_int4")
        assert qw.shape == (16, 16)  # packed along in_features
        # unpack == the unpacked quantization (sign-preserving nibbles)
        full = jnp.clip(jnp.round(w / (jnp.max(jnp.abs(w), 0) / 7.0
                                       + 1e-12)), -7, 7).astype(jnp.int8)
        np.testing.assert_array_equal(np.asarray(Q._unpack_int4(qw)),
                                      np.asarray(full))

    def test_groupwise_beats_per_channel_on_outliers(self, rng):
        # one huge outlier per column ruins a per-channel scale; group
        # scales contain the damage to the outlier's group
        w = rng.standard_normal((128, 8)).astype(np.float32)
        w[0] *= 50.0
        w = jnp.asarray(w)
        qc, sc = Q.weight_quantize(w, algo="weight_only_int4")
        qg, sg = Q.weight_quantize(w, algo="weight_only_int4",
                                   group_size=32)
        assert sg.shape == (4, 8)
        # rows OUTSIDE the outlier's group: group scales recover full
        # precision there, the per-channel scale stays poisoned everywhere
        ec = float(jnp.abs(Q.weight_dequantize(
            qc, sc, algo="weight_only_int4") - w)[32:].max())
        eg = float(jnp.abs(Q.weight_dequantize(
            qg, sg, algo="weight_only_int4", group_size=32) - w)[32:].max())
        assert eg < ec / 4

    def test_rejects_bad_inputs(self):
        with pytest.raises(ValueError):
            Q.weight_quantize(jnp.ones((4, 4)), algo="int42")
        with pytest.raises(ValueError):
            Q.weight_quantize(jnp.ones((5, 4)), algo="weight_only_int4")
        with pytest.raises(ValueError):
            Q.weight_quantize(jnp.ones((8, 4)), group_size=3)
        with pytest.raises(ValueError):
            Q.weight_only_linear(jnp.ones((2, 8)),
                                 jnp.ones((8, 4), jnp.int8))


class TestWeightOnlyLinear:
    def test_int8_matmul_close(self, rng):
        x = jnp.asarray(rng.standard_normal((4, 64)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((64, 48)).astype(np.float32))
        qw, s = Q.weight_quantize(w)
        y = Q.weight_only_linear(x, qw, weight_scale=s)
        ref = x @ w
        rel = float(jnp.abs(y - ref).max() / jnp.abs(ref).max())
        assert rel < 0.02, rel

    def test_int4_grouped_matmul_close(self, rng):
        x = jnp.asarray(rng.standard_normal((4, 64)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((64, 48)).astype(np.float32))
        qw, s = Q.weight_quantize(w, algo="weight_only_int4",
                                  group_size=16)
        y = Q.weight_only_linear(x, qw, weight_scale=s,
                                 weight_dtype="int4", group_size=16)
        ref = x @ w
        rel = float(jnp.abs(y - ref).max() / jnp.abs(ref).max())
        assert rel < 0.12, rel

    def test_bias_and_batch_dims(self, rng):
        x = jnp.asarray(rng.standard_normal((2, 3, 32)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((16,)).astype(np.float32))
        qw, s = Q.weight_quantize(w)
        y = Q.weight_only_linear(x, qw, bias=b, weight_scale=s)
        assert y.shape == (2, 3, 16)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w + b),
                                   rtol=0.05, atol=0.05)

    def test_llm_int8_outlier_decomposition(self, rng):
        x = rng.standard_normal((4, 64)).astype(np.float32)
        x[:, 7] *= 30.0  # one loud feature channel
        x = jnp.asarray(x)
        w = jnp.asarray(rng.standard_normal((64, 48)).astype(np.float32))
        qw, s = Q.weight_quantize(w, algo="llm.int8")
        y = Q.llm_int8_linear(x, qw, weight_scale=s, threshold=6.0)
        ref = x @ w
        rel = float(jnp.abs(y - ref).max() / jnp.abs(ref).max())
        assert rel < 0.02, rel

    def test_jit_and_grad_free(self, rng):
        # serving path must jit cleanly; int8 weight is a traced input
        x = jnp.asarray(rng.standard_normal((2, 32)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((32, 8)).astype(np.float32))
        qw, s = Q.weight_quantize(w)
        f = jax.jit(lambda x, qw, s: Q.weight_only_linear(
            x, qw, weight_scale=s))
        np.testing.assert_allclose(np.asarray(f(x, qw, s)),
                                   np.asarray(Q.weight_only_linear(
                                       x, qw, weight_scale=s)), rtol=1e-6)


class TestQuantizeModel:
    def test_quantize_linears_swaps_and_matches(self, rng):
        pt.seed(0)
        m = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 32))
        x = jnp.asarray(rng.standard_normal((4, 32)).astype(np.float32))
        ref = m(x)
        n = Q.quantize_linears(m)
        assert n == 2
        y = m.eval()(x)
        rel = float(jnp.abs(y - ref).max() / jnp.abs(ref).max())
        assert rel < 0.03, rel
        # quantized weights live in state_dict as buffers
        sd = m.state_dict()
        assert sd["0.weight"].dtype == jnp.int8
        assert "0.weight_scale" in sd

    def test_predicate_filters(self):
        pt.seed(0)
        m = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        n = Q.quantize_linears(m, predicate=lambda name, l: name == "0")
        assert n == 1
        from paddle_tpu.nn.layers_common import Linear
        assert isinstance(m[1], Linear)

    def test_fused_multi_transformer_quantized_decode(self, rng):
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        pt.seed(0)
        m = FusedMultiTransformer(32, 4, 64, num_layers=2)
        x = jnp.asarray(rng.standard_normal((2, 5, 32)).astype(np.float32))
        ref, ref_c = m(x, caches=m.init_cache(2, 16))
        n = m.quantize_weights()
        assert n == 2 * 4  # qkv/out/ffn1/ffn2 per layer
        out, c = m(x, caches=m.init_cache(2, 16))
        scale = float(jnp.std(ref))
        err = float(jnp.abs(out - ref).max()) / scale
        # bounded above AND below zero: err == 0 would mean the swap
        # silently didn't take effect (the float path still running)
        assert 0 < err < 0.1, err
        tok = jnp.asarray(rng.standard_normal((2, 1, 32)).astype(np.float32))
        lens = jnp.array([5, 5], jnp.int32)
        d, _ = m(tok, caches=c, seq_lens=lens)
        dref, _ = m(tok, caches=ref_c, seq_lens=lens)  # quantized weights both
        assert d.shape == dref.shape

    def test_generate_logit_error_bound(self):
        """The serving quality gate: weight-only int8 on a tiny llama —
        teacher-forced logit error vs the bf16 model stays bounded, and
        generate() runs end-to-end on the quantized model."""
        from paddle_tpu.models.llama import llama
        pt.seed(0)
        model = llama("tiny", max_position_embeddings=96)
        model.eval()
        ids = jax.random.randint(jax.random.key(0), (2, 16), 0,
                                 model.cfg.vocab_size)
        toks = jax.random.randint(jax.random.key(5), (2, 8), 0,
                                  model.cfg.vocab_size)

        def rollout(m):
            caches = m.model.init_cache(2, 96)
            _, caches = m.model(ids, caches=caches)
            lens = jnp.full((2,), 16, jnp.int32)
            out = []
            for t in range(8):
                h, caches = m.model(toks[:, t:t + 1], caches=caches,
                                    seq_lens=lens)
                out.append(m.logits(h[:, -1]))
                lens = lens + 1
            return jnp.stack(out)

        fp = rollout(model)
        n = Q.quantize_linears(model.model)
        assert n > 0
        q = rollout(model)
        scale = float(jnp.std(fp))
        err = float(jnp.abs(fp - q).max()) / scale
        # err == 0 would mean quantization silently didn't take effect
        assert 0 < err < 0.35, f"relative logit error {err}"
        assert float(jnp.abs(fp - q).mean()) / scale < 0.05
        # e2e generate on the quantized model (weights ride the params
        # pytree as buffers via serving_params, not baked constants)
        out = model.generate(ids, max_new_tokens=8)
        assert out.shape == (2, 24)
        # stacked: weight-only int8 + int8 KV cache
        out2 = model.generate(ids, max_new_tokens=8, kv_cache_dtype="int8")
        assert out2.shape == (2, 24)
